"""Configuration layer: dataclasses + presets + YAML + CLI overrides.

TPU-native re-design of the reference's config system
(/root/reference/mingpt/model.py:38-59, /root/reference/mingpt/trainer.py:21-29,
/root/reference/mingpt/char_dataset.py:12-17, /root/reference/mingpt/train.py:36-39,
/root/reference/mingpt/gpt2_config.yaml): the same four-section schema
(model / optimizer / data / trainer), with the reference's latent config bugs
fixed by construction:

* one canonical spelling ``n_embd`` everywhere (the reference mixed ``n_embed``
  and ``n_embd`` across dataclass, preset table, and YAML — bugs B2/B15 in
  SURVEY.md §2.9); ``n_embed`` is accepted as an input alias and normalised.
* preset-vs-explicit dims validated as XOR (the reference's condition at
  model.py:267 inverted the check — bug B1), matching upstream minGPT's intent.
* unknown keys are rejected at load time with the valid key set in the error.

No Hydra dependency: a plain YAML file plus dotted ``section.key=value`` CLI
overrides reproduces the Hydra surface actually used by the reference
(/root/reference/mingpt/train.py:30, gpt2_config.yaml), without relocating the
run dir (the reference had to disable that relocation, gpt2_config.yaml:21-23).
"""

from __future__ import annotations

import dataclasses
import io
import warnings
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional, Sequence, Tuple

import yaml

# ---------------------------------------------------------------------------
# Model presets
# ---------------------------------------------------------------------------

# Preset table mirroring /root/reference/mingpt/model.py:269-294 (values are
# public GPT-2/minGPT lore, cf. reference README.md:86-143), plus TPU-era
# additions (llama family for the RoPE/SwiGLU retrofit, BASELINE config #5).
MODEL_PRESETS: dict[str, dict[str, Any]] = {
    # name            layers heads  width   (params)
    "openai-gpt":    dict(n_layer=12, n_head=12, n_embd=768),    # 117M
    "gpt2":          dict(n_layer=12, n_head=12, n_embd=768),    # 124M
    "gpt2-medium":   dict(n_layer=24, n_head=16, n_embd=1024),   # 350M
    "gpt2-large":    dict(n_layer=36, n_head=20, n_embd=1280),   # 774M
    "gpt2-xl":       dict(n_layer=48, n_head=25, n_embd=1600),   # 1558M
    "gopher-44m":    dict(n_layer=8,  n_head=16, n_embd=512),
    "gpt-mini":      dict(n_layer=6,  n_head=6,  n_embd=192),
    "gpt-micro":     dict(n_layer=4,  n_head=4,  n_embd=128),
    "gpt-nano":      dict(n_layer=3,  n_head=3,  n_embd=48),
    # Llama-style presets (rotary + SwiGLU + RMSNorm), beyond-parity targets.
    "llama-tiny":    dict(n_layer=4,  n_head=4,  n_embd=256,  n_kv_head=2,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False),
    "llama-3-8b":    dict(n_layer=32, n_head=32, n_embd=4096, n_kv_head=8,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False,
                          vocab_size=128256, block_size=8192, ffn_mult=3.5,
                          rope_theta=500000.0),  # Llama 3 base, not the 1e4 default
    # Mistral-style presets: Llama architecture + sliding-window attention
    # (each position attends the last `attention_window` tokens; the flash
    # kernel skips out-of-band blocks so compute is O(T*window)).
    "mistral-tiny":  dict(n_layer=4,  n_head=4,  n_embd=256,  n_kv_head=2,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False,
                          attention_window=64),
    "mistral-7b":    dict(n_layer=32, n_head=32, n_embd=4096, n_kv_head=8,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False,
                          vocab_size=32000, block_size=8192, ffn_mult=3.5,
                          rope_theta=1000000.0, attention_window=4096),
    # Mixtral-style sparse MoE presets (SwiGLU experts, top-2 routing,
    # expert axis shards over the mesh's ep axis — ops/moe.py).
    "mixtral-tiny":  dict(n_layer=4,  n_head=4,  n_embd=256,  n_kv_head=2,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False,
                          n_experts=4, moe_top_k=2),
    "mixtral-8x7b":  dict(n_layer=32, n_head=32, n_embd=4096, n_kv_head=8,
                          rope=True, swiglu=True, rmsnorm=True, tie_weights=False,
                          vocab_size=32000, block_size=8192, ffn_mult=3.5,
                          rope_theta=1000000.0, n_experts=8, moe_top_k=2),
}


class ConfigError(ValueError):
    """Raised for invalid or inconsistent configuration."""


def _reject_unknown(cls, kwargs: Mapping[str, Any]) -> dict[str, Any]:
    valid = {f.name for f in dataclasses.fields(cls)}
    unknown = set(kwargs) - valid
    if unknown:
        raise ConfigError(
            f"{cls.__name__}: unknown key(s) {sorted(unknown)}; "
            f"valid keys: {sorted(valid)}"
        )
    return dict(kwargs)


@dataclass(frozen=True)
class GPTConfig:
    """Model hyperparameters (reference GPTConfig, model.py:38-51).

    Either give ``model_type`` (a preset name) or the explicit dims
    ``n_layer/n_head/n_embd`` — exactly one of the two (upstream minGPT's
    XOR assert; the reference fork broke this, SURVEY.md B1).

    Frozen (hashable): instances are jit static arguments; evolve with
    ``dataclasses.replace``.
    """

    model_type: Optional[str] = None
    n_layer: Optional[int] = None
    n_head: Optional[int] = None
    n_embd: Optional[int] = None
    vocab_size: int = 50257
    block_size: int = 1024
    # Dropout rates (reference: embed_drop/resid_drop/attn_drop, all 0.1).
    embd_pdrop: float = 0.1
    resid_pdrop: float = 0.1
    attn_pdrop: float = 0.1
    # --- TPU-native extensions -------------------------------------------
    # Attention implementation: "einsum" (reference semantics, oracle),
    # "flash" (Pallas blockwise kernel), "ring" (sequence-parallel ring
    # attention over the mesh's `sp` axis).
    attention: str = "einsum"
    # Sliding-window (banded) attention, Mistral-style: each position sees
    # only the last `attention_window` tokens (itself included); None =
    # full causal. Supported by every attention impl: the einsum oracle,
    # the flash kernel (which skips out-of-band blocks: compute
    # O(T*window), not O(T^2)), and the ring/ulysses sequence-parallel
    # paths (the ring turns banded with static hop skipping —
    # test_sp_window_softcap.py).
    attention_window: Optional[int] = None
    # Gemma-2-style logit soft-capping: logits -> cap * tanh(logits / cap).
    # `attn_logit_softcap` applies to attention scores before masking
    # (every impl, incl. ring/ulysses — test_sp_window_softcap.py);
    # `final_logit_softcap` applies to the LM-head logits (loss, chunked
    # loss, and generation alike). None disables.
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    # Compute dtype for activations; params are kept in float32.
    dtype: str = "bfloat16"
    # Rematerialise each block in backward (jax.checkpoint) to trade FLOPs
    # for HBM.
    remat: bool = False
    # GPipe microbatch count when the mesh has pp > 1 stages; 0 = one
    # microbatch per stage. Bubble fraction is (pp-1)/(M+pp-1), so raise M
    # for efficiency, bounded by batch divisibility and activation memory.
    pp_microbatches: int = 0
    # Pipeline schedule: "gpipe" (plain differentiable scan; autodiff derives
    # the backward pipeline; live activations O(M) microbatches) or "1f1b"
    # (custom-vjp backward that interleaves recompute-forward with backward
    # in 1F1B order, bounding the backward's stage-input stash to O(pp)
    # microbatches at the cost of one extra forward per stage-microbatch
    # vs gpipe+remat — pick it when activation HBM, not FLOPs, binds).
    pp_schedule: str = "gpipe"
    # Tie the LM head to the token embedding (GPT-2 ties; the reference's
    # head is an independent bias-free Linear, model.py:249 — keep that as
    # the default for parity).
    tie_weights: bool = False
    # Llama-retrofit toggles (BASELINE config #5).
    rope: bool = False
    rope_theta: float = 10000.0
    swiglu: bool = False
    rmsnorm: bool = False
    n_kv_head: Optional[int] = None  # grouped-query attention; None = n_head
    ffn_mult: float = 4.0  # MLP expansion factor (reference hardcodes 4x)
    norm_eps: float = 1e-5  # LayerNorm/RMSNorm epsilon
    # Mixture-of-experts (ops/moe.py): 0 = dense MLP (reference semantics);
    # E > 0 replaces every block's MLP with E GELU experts, top-k routed,
    # expert axis sharded over the mesh's `ep` axis.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01  # load-balancing loss weight
    # Cross-entropy head chunking: >1 splits the LM-head matmul + softmax
    # into this many sequence chunks under jax.checkpoint, so the (B, T, V)
    # fp32 logits tensor — the dominant activation at GPT-2 vocab sizes —
    # never materialises whole. 0/1 = dense (reference semantics; identical
    # loss either way). Ignored when T is not divisible by it.
    loss_chunks: int = 8
    # lax.scan unroll factor for the layer loop (>= 1; lax.scan handles a
    # non-dividing remainder): >1 lets XLA fuse across layer boundaries at
    # the cost of compile time.
    scan_unroll: int = 1
    # Replace the layer lax.scan with a statically unrolled python loop.
    # The scan stacks every saved-for-backward activation into (n_layer,
    # ...) buffers via dynamic-update-slice — ~23% of step time on the
    # round-4 TPU trace (bitcast_dynamic-update-slice fusions). Unrolled,
    # XLA plans each layer's residuals as individual statically-addressed
    # buffers: no stacking copies, better fusion across the layer
    # boundary, at the cost of an n_layer-times-larger HLO (slower
    # compile). Ignored under pp (the pipeline has its own schedule).
    unroll_layers: bool = False

    @classmethod
    def make(cls, **kwargs: Any) -> "GPTConfig":
        """Build + resolve + validate in one step (accepts n_embed alias)."""
        kwargs = dict(kwargs)
        if "n_embed" in kwargs:  # normalise the reference's stray spelling
            kwargs.setdefault("n_embd", kwargs.pop("n_embed"))
        cfg = cls(**_reject_unknown(cls, kwargs))
        return cfg.resolved()

    def resolved(self) -> "GPTConfig":
        """Apply the preset table and validate (XOR semantics, fixing B1)."""
        type_given = self.model_type is not None
        dims_given = all(
            v is not None for v in (self.n_layer, self.n_head, self.n_embd)
        )
        any_dim_given = any(
            v is not None for v in (self.n_layer, self.n_head, self.n_embd)
        )
        if type_given and any_dim_given:
            raise ConfigError(
                "give either model_type (a preset) or explicit "
                "n_layer/n_head/n_embd, not both"
            )
        if not type_given and not dims_given:
            raise ConfigError(
                "model underspecified: give model_type or all of "
                "n_layer/n_head/n_embd"
            )
        out = self
        if type_given:
            if self.model_type not in MODEL_PRESETS:
                raise ConfigError(
                    f"unknown model_type {self.model_type!r}; "
                    f"presets: {sorted(MODEL_PRESETS)}"
                )
            out = dataclasses.replace(self, **MODEL_PRESETS[self.model_type])
        out.validate()
        return out

    def validate(self) -> None:
        if self.n_embd is None or self.n_head is None or self.n_layer is None:
            raise ConfigError("model dims unresolved; call .resolved() first")
        if self.n_embd % self.n_head != 0:
            raise ConfigError(
                f"n_embd={self.n_embd} not divisible by n_head={self.n_head}"
            )
        kv = self.n_kv_head if self.n_kv_head is not None else self.n_head
        if self.n_head % kv != 0:
            raise ConfigError(
                f"n_head={self.n_head} not divisible by n_kv_head={kv}"
            )
        if self.attention not in ("einsum", "flash", "ring", "ulysses"):
            raise ConfigError(f"unknown attention impl {self.attention!r}")
        # window/softcap compose with every attention impl, including the
        # sequence-parallel ones: the ring turns banded with static hop
        # skipping and ulysses holds the full sequence locally (r4 —
        # parallel/ring_attention.py, parallel/ulysses.py)
        if self.attention_window is not None and self.attention_window < 1:
            raise ConfigError(
                f"attention_window must be >= 1, got {self.attention_window}"
            )
        if self.attn_logit_softcap is not None and self.attn_logit_softcap <= 0:
            raise ConfigError(
                f"attn_logit_softcap must be > 0, got {self.attn_logit_softcap}"
            )
        if self.final_logit_softcap is not None and self.final_logit_softcap <= 0:
            raise ConfigError(
                f"final_logit_softcap must be > 0, got {self.final_logit_softcap}"
            )
        if self.scan_unroll < 1:
            raise ConfigError(f"scan_unroll must be >= 1, got {self.scan_unroll}")
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ConfigError(
                f"unknown pp_schedule {self.pp_schedule!r} "
                "(choose 'gpipe' or '1f1b')"
            )
        if self.loss_chunks < 0:
            raise ConfigError(f"loss_chunks must be >= 0, got {self.loss_chunks}")
        if self.rope and (self.n_embd // self.n_head) % 2 != 0:
            raise ConfigError(
                f"rope needs an even head_dim, got {self.n_embd // self.n_head}"
            )
        if self.block_size <= 0 or self.vocab_size <= 0:
            raise ConfigError("block_size and vocab_size must be positive")
        if self.n_experts:
            if self.moe_top_k < 1 or self.moe_top_k > self.n_experts:
                raise ConfigError(
                    f"moe_top_k={self.moe_top_k} outside [1, {self.n_experts}]"
                )

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head if self.n_kv_head is not None else self.n_head


@dataclass
class OptimizerConfig:
    """Reference OptimizerConfig (model.py:54-59): GPT-3 AdamW values."""

    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    betas: Tuple[float, float] = (0.9, 0.95)
    eps: float = 1e-8
    # --- extensions: the LR schedule lore the reference README records
    # (warmup + cosine, README.md:93,125) but the reference never implements.
    schedule: str = "constant"  # "constant" | "cosine"
    warmup_steps: int = 0
    total_steps: Optional[int] = None  # required for cosine
    min_lr_ratio: float = 0.1

    def __post_init__(self) -> None:
        if isinstance(self.betas, list):
            self.betas = tuple(self.betas)  # YAML gives lists

    @classmethod
    def make(cls, **kwargs: Any) -> "OptimizerConfig":
        return cls(**_reject_unknown(cls, kwargs))


@dataclass
class DataConfig:
    """Reference DataConfig (char_dataset.py:12-17) + tokenizer selection."""

    path: str = ""
    block_size: int = 128
    train_split: float = 0.9
    truncate: float = 1.0
    # --- extensions ------------------------------------------------------
    # "char" = reference behavior; "bpe" = byte-level BPE (data/bpe.py):
    # trained on the corpus to bpe_vocab_size, or loaded from bpe_path
    # (a tokenizer saved with BPETokenizer.save, or trained earlier).
    tokenizer: str = "char"
    bpe_vocab_size: int = 512
    bpe_path: Optional[str] = None

    @classmethod
    def make(cls, **kwargs: Any) -> "DataConfig":
        cfg = cls(**_reject_unknown(cls, kwargs))
        if not (0.0 < cfg.train_split <= 1.0):
            raise ConfigError(f"train_split={cfg.train_split} outside (0, 1]")
        if not (0.0 < cfg.truncate <= 1.0):
            raise ConfigError(f"truncate={cfg.truncate} outside (0, 1]")
        if cfg.tokenizer not in ("char", "bpe"):
            raise ConfigError(f"unknown tokenizer {cfg.tokenizer!r}")
        return cfg


@dataclass
class MeshConfig:
    """Device-mesh shape for pjit/shard_map parallelism.

    Replaces the reference's implicit "one process per GPU, DDP over all"
    topology (trainer.py:71, slurm_run.sh:17-23) with an explicit named mesh:
    ``pp`` (pipeline stages), ``dp`` (data), ``fsdp`` (param shards), ``ep``
    (experts — also shards the batch, GShard-style), ``tp`` (tensor), ``sp``
    (sequence, for ring attention). -1 means "absorb all remaining devices".
    """

    pp: int = 1
    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @classmethod
    def make(cls, **kwargs: Any) -> "MeshConfig":
        return cls(**_reject_unknown(cls, kwargs))


@dataclass
class TrainerConfig:
    """Reference GPTTrainerConfig (trainer.py:21-29) + TPU extensions."""

    max_epochs: int = 10
    batch_size: int = 64  # global batch, split across the dp axis
    grad_norm_clip: float = 1.0
    snapshot_path: Optional[str] = None
    save_every: int = 1  # epochs between snapshots
    # kept for schema parity with the reference (unused there too —
    # the optimizer owns the LR); warn-level ignored.
    learning_rate: Optional[float] = None
    dl_num_workers: int = 0
    # --- extensions ------------------------------------------------------
    seed: int = 0
    log_every: int = 100          # steps between metric lines (reference: 100)
    eval_every: int = 1           # epochs between eval passes
    eval_batches: Optional[int] = None  # cap eval batches; None = full pass
    metrics_jsonl: Optional[str] = None  # JSONL metrics sink (§5.5 upgrade)
    tensorboard_dir: Optional[str] = None  # TensorBoard sink (§5.5 upgrade)
    # Write msgpack snapshots from a background thread (the host copy is
    # taken synchronously; serialization + object-store IO overlap training).
    async_save: bool = False
    # Multi-host msgpack saves gather the FULL state to EVERY host
    # (process_allgather) before process 0 writes the single blob — fine at
    # gpt2-124M, hopeless for billion-parameter state on a pod. Saves above
    # this many MB refuse with a pointer to the Orbax backend (sharded
    # collective writes, no gather; use a snapshot_path without the
    # .msgpack suffix). Raise deliberately if your hosts really have the
    # RAM and you want the single-blob format anyway.
    msgpack_gather_limit_mb: int = 8192
    # --- durability (training/durability.py) -----------------------------
    # Checkpoints retained in the commit manifest (keep-last-K rotation);
    # older step objects are deleted after the manifest stops referencing
    # them. >= 2 gives corruption-aware restore something to fall back to.
    keep_snapshots: int = 3
    # Retry budget for transient fsspec I/O around snapshot save/load
    # (exponential backoff + jitter; missing/permanent errors never retry).
    io_retries: int = 4
    io_retry_delay_s: float = 0.5   # base backoff delay (0 = no sleep, tests)
    # Install SIGTERM/SIGINT handlers in train(): request a stop at the
    # next step boundary, snapshot, and exit requeue-friendly (the
    # preemption contract of TPU spot/preemptible VMs). Only takes effect
    # in the main thread; False restores the previous die-mid-step behavior.
    handle_signals: bool = True
    # Accumulate gradients over this many micro-batches per optimizer step
    # (one lax.scan inside the same jitted step): activation memory scales
    # with batch_size/grad_accum_steps, semantics stay the full batch.
    grad_accum_steps: int = 1
    # ZeRO-style cross-replica weight-update sharding over the dp axis
    # (ISSUE 9, arXiv 2004.13336): reduce-scatter grads, run the optimizer
    # on the local 1/dp shard, allgather params; Adam moments are
    # physically 1/dp per device. Loss/param parity with the replicated
    # update (train.py --selftest-zero). No-op at dp=1; requires the
    # msgpack checkpoint backend (canonical-layout snapshots reshard to
    # any dp extent on restore).
    zero_dp: bool = False
    prefetch: int = 2  # background batch-prefetch depth; 0 disables
    # debug aids (SURVEY §5.2 — the reference shipped a real checkpoint race
    # and had no sanitizers): jax_debug_nans traps the first NaN/Inf inside
    # the compiled step instead of letting training silently diverge.
    debug_nans: bool = False
    mesh: MeshConfig = field(default_factory=MeshConfig)
    profile_dir: Optional[str] = None   # jax.profiler trace output
    profile_steps: Tuple[int, int] = (10, 20)
    max_steps: Optional[int] = None     # step cap (for benches/smoke runs)
    # --- telemetry (ISSUE 5) ---------------------------------------------
    # Serve /metrics (Prometheus text) + /healthz from process 0 on this
    # port; 0 disables. Negative values are rejected at bind time. Use a
    # fixed port for scrapers; the serving path's --metrics-port 0 idiom
    # (ephemeral) is for tests, where TrainerConfig keeps 0 = off because
    # a training job has no caller to read the bound port back.
    metrics_port: int = 0
    # Stream trainer spans (step/eval/snapshot timings) to this JSONL file
    # from process 0; feeds tools/trace_summary.py. None = ring buffer only.
    spans_jsonl: Optional[str] = None

    @classmethod
    def make(cls, **kwargs: Any) -> "TrainerConfig":
        kwargs = dict(kwargs)
        mesh = kwargs.pop("mesh", None)
        cfg = cls(**_reject_unknown(cls, {**kwargs}))
        if mesh is not None:
            cfg.mesh = mesh if isinstance(mesh, MeshConfig) else MeshConfig.make(**mesh)
        if isinstance(cfg.profile_steps, list):
            cfg.profile_steps = tuple(cfg.profile_steps)
        if cfg.learning_rate is not None:
            warnings.warn(
                "TrainerConfig.learning_rate is accepted for schema parity "
                "with the reference (trainer.py:21-29) but IGNORED — the "
                "optimizer owns the learning rate; set "
                "optimizer_config.learning_rate instead.",
                UserWarning,
                stacklevel=2,
            )
        return cfg


@dataclass
class ExperimentConfig:
    """The four-section bundle the reference unpacks at train.py:36-39."""

    gpt_config: GPTConfig
    optimizer_config: OptimizerConfig
    data_config: DataConfig
    trainer_config: TrainerConfig

    SECTIONS = {
        "gpt_config": GPTConfig,
        "optimizer_config": OptimizerConfig,
        "data_config": DataConfig,
        "trainer_config": TrainerConfig,
    }

    @classmethod
    def from_dict(cls, raw: Mapping[str, Any]) -> "ExperimentConfig":
        unknown = set(raw) - set(cls.SECTIONS)
        if unknown:
            raise ConfigError(
                f"unknown config section(s) {sorted(unknown)}; "
                f"valid: {sorted(cls.SECTIONS)}"
            )
        return cls(
            gpt_config=GPTConfig.make(**dict(raw.get("gpt_config", {}))),
            optimizer_config=OptimizerConfig.make(
                **dict(raw.get("optimizer_config", {}))
            ),
            data_config=DataConfig.make(**dict(raw.get("data_config", {}))),
            trainer_config=TrainerConfig.make(
                **dict(raw.get("trainer_config", {}))
            ),
        )

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# YAML + CLI overrides
# ---------------------------------------------------------------------------


def _parse_override_value(text: str) -> Any:
    """Parse an override value with YAML scalar rules (1 -> int, true -> bool).

    YAML 1.1 quirk: ``1e-3`` (no dot) parses as a string; accept it as a float
    the way every CLI user expects.
    """
    value = yaml.safe_load(io.StringIO(text))
    if isinstance(value, str):
        try:
            return int(value)
        except ValueError:
            pass
        try:
            return float(value)
        except ValueError:
            pass
    return value


def apply_overrides(raw: dict[str, Any], overrides: Sequence[str]) -> dict[str, Any]:
    """Apply ``section.key=value`` dotted overrides (the Hydra CLI surface).

    ``section.key=value`` sets; ``~section.key`` deletes. Nested keys use
    further dots (e.g. ``trainer_config.mesh.dp=4``).
    """
    out = {k: (dict(v) if isinstance(v, Mapping) else v) for k, v in raw.items()}
    for ov in overrides:
        ov = ov.strip()
        if not ov:
            continue
        if ov.startswith("~"):
            path, value, delete = ov[1:], None, True
        elif "=" in ov:
            path, text = ov.split("=", 1)
            value, delete = _parse_override_value(text), False
        else:
            raise ConfigError(f"malformed override {ov!r}; want key=value or ~key")
        keys = path.split(".")
        node = out
        for k in keys[:-1]:
            nxt = node.get(k)
            if not isinstance(nxt, dict):
                nxt = dict(nxt) if isinstance(nxt, Mapping) else {}
                node[k] = nxt
            node = nxt
        if delete:
            node.pop(keys[-1], None)
        else:
            node[keys[-1]] = value
    return out


def load_config(
    path: Optional[str] = None, overrides: Sequence[str] = ()
) -> ExperimentConfig:
    """Load a YAML config file and apply CLI overrides.

    Replaces the reference's @hydra.main + manual dataclass unpacking
    (train.py:30-39) with the same observable behavior: a four-section YAML,
    each section validated into its dataclass, any key overridable from the
    command line as ``section.key=value``.
    """
    raw: dict[str, Any] = {}
    if path is not None:
        with open(path) as f:
            loaded = yaml.safe_load(f) or {}
        if not isinstance(loaded, Mapping):
            raise ConfigError(f"config file {path} is not a mapping")
        raw = {k: v for k, v in loaded.items() if k != "hydra"}
    raw = apply_overrides(raw, overrides)
    return ExperimentConfig.from_dict(raw)
