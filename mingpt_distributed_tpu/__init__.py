"""mingpt_distributed_tpu: a TPU-native (JAX/XLA/Pallas/pjit) training framework
with the capabilities of aponte411/minGPT-distributed, rebuilt from scratch.

Layer map (mirrors SURVEY.md §1, TPU-first):
  L0 launch/    — TPU pod bring-up + run-on-all-workers (slurm/ analogue)
  L1 parallel/  — mesh, shardings, collectives, multi-host init (NCCL/DDP analogue)
  L2 models/ ops/ — pure-function model over pytrees + Pallas kernels
  L3 training/  — train step, trainer loop, optimizer, checkpoint (trainer.py analogue)
  L4 config.py, data/, train.py — config, dataset, application entry
"""

from mingpt_distributed_tpu.config import (
    ConfigError,
    DataConfig,
    ExperimentConfig,
    GPTConfig,
    MeshConfig,
    MODEL_PRESETS,
    OptimizerConfig,
    TrainerConfig,
    apply_overrides,
    load_config,
)

__version__ = "0.1.0"
