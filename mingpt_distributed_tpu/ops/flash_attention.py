"""Causal flash attention — Pallas TPU kernel (FlashAttention-2 style).

Replaces, on the hot path, the einsum oracle in ops/attention.py (itself the
intended semantics of the reference's fused torch attention,
/root/reference/mingpt/model.py:147-165): same math, different memory story.
The einsum path materialises the (B, H, T, S) logits in HBM; this kernel
streams K/V blocks through VMEM with an online softmax, so attention memory
is O(T·d) — the property that makes long block_size HBM-feasible
(SURVEY §5.7's prescription for this framework).

Shapes follow ops.attention.causal_attention: q (B, T, H, hd), k/v
(B, S, KV, hd) with GQA handled by broadcasting outside the kernel (autodiff
then sums dk/dv over the query-head group for free).

Tiling: every kernel streams K/V (or Q, for dk/dv) **block-by-block through
the grid** — the per-cell VMEM footprint is O(block·hd + block²) regardless
of sequence length, so the shipped llama presets (block_size 8192) fit VMEM.
The sequential innermost grid dimension carries the online-softmax state
(running max m, denominator l, accumulator acc) in VMEM scratch across k
blocks; causality is enforced at block granularity by skipping cells above
the diagonal, whose index maps clamp to the diagonal so Pallas's revisit
optimisation never re-DMAs a block that won't be used.

Forward: grid (B*H, T/B, T/B) with the k-block index innermost; emits the
log-sum-exp per row for the backward.
Backward: two kernels — dq streams K/V blocks per q block; dk/dv streams
Q/dO blocks per k block — both recomputing probabilities from the saved LSE.
No stored attention matrix anywhere. The native-layout path fuses the two
into one dq+dk+dv kernel when its dq scratch fits VMEM (_dqkv_kernel_btd).

Falls back to the einsum oracle when the shape/config doesn't fit the kernel
(attention dropout on, decode-time cross lengths, T not a multiple of the
block) — correctness is never gated on the fast path. On CPU the kernel runs
in Pallas interpret mode, which is how the parity tests exercise it.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.utils import compat

NEG_INF = -1e30

# Base-2 softmax rebase (round-5, measured): the VPU evaluates exp2 ~6%
# faster than exp (tools/exp_exp2.py: 72.4 vs 68.2 G/s), and log2(e) folds
# into the attention scale constant, so every kernel tracks scores, running
# max and alpha in base 2 at ZERO extra per-element ops — exp becomes exp2,
# nothing else changes. The saved log-sum-exp stays in the NATURAL domain
# (one per-row multiply at finalize): ring-attention merging and the dlse
# cotangent contract are unchanged. exp2(x * LOG2E) == exp(x).
LOG2E = 1.4426950408889634
INV_LOG2E = 1.0 / LOG2E


def _scores_base2(q, kblk, scale, softcap):
    """The shared per-cell score computation: QK^T -> optional softcap ->
    BASE-2 scores with the rebase constants folded in (see LOG2E note).

    Returns (s, t): s = base-2 scores, t = the raw tanh output when
    softcap is active (the backward's derivative factor is 1 - t*t;
    kept UNMASKED so it stays bounded in [0, 1]), else None. One
    definition for all six kernels — the math must never diverge between
    them.
    """
    s = jax.lax.dot_general(
        q, kblk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if softcap is not None:
        t = jnp.tanh(s * (scale / softcap))
        return (softcap * LOG2E) * t, t
    return s * (scale * LOG2E), None


def _btd_applies(h: int, hd: int) -> bool:
    """Whether causal_attention routes (h, hd) to the native-(B,T,D)
    kernels — directly packed, or via odd-head zero padding. bench.py
    records its layout metadata through THIS predicate so the artifact
    cannot drift from the real dispatch."""
    return _btd_pack(h, hd) is not None or (hd < 128 and 128 % hd == 0)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported_block(t: int) -> Optional[int]:
    """Public applicability probe: the square block size the kernel would
    tile T with, or None when the kernel doesn't apply (callers — e.g. the
    ring-attention dispatch — must then use an oracle path)."""
    return _block_sizes(t)


def _block_sizes(t: int) -> Optional[int]:
    """Pick a square block size dividing T, or None if the kernel won't fit.

    ``FLASH_BLOCK`` overrides the preference order (bench.py sweeps it on
    hardware — VERDICT r2 weak #4: the fixed (512, 256, 128) ladder had no
    measured justification): the override is used when it divides T, else
    the default ladder applies.
    """
    override = os.environ.get("FLASH_BLOCK")
    if override:
        try:
            ob = int(override)
        except ValueError:
            ob = 0
        # Clamp to the validated ladder range: above 512 the (block, block)
        # fp32 scratch outgrows VMEM and Mosaic compile fails at trace time,
        # and a process-global env var would poison ring-attention dispatch
        # for every caller, not just the sweep that set it.
        if 8 <= ob <= 512 and t % ob == 0:
            return ob
    for b in (512, 256, 128):
        if t % b == 0:
            return b
    if t <= 128 and t % 8 == 0:
        return t
    return None


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _dispatch_cells(compute, qi, kj, block, active, *, causal, window,
                    q_offset=0):
    """Route one grid cell to ``compute(masked)`` — the ONE definition of
    the masked/full cell classification for all six kernels. Under a
    window every active cell keeps the masked body (band edges cross
    cells); plain causal splits active cells into the masked diagonal
    and the mask-free interior (min q_pos at or past max k_pos, which
    generalises "strictly below the diagonal" to the ring's q_offset
    hops — full cells also cannot hold dead rows, so their p needs no
    structural mask); non-causal is always mask-free."""
    if causal and window is not None:
        @pl.when(active)
        def _m():
            compute(True)
    elif causal:
        cell_full = (q_offset + qi * block) >= (kj + 1) * block - 1

        @pl.when(active & ~cell_full)
        def _diag():
            compute(True)

        @pl.when(active & cell_full)
        def _full():
            compute(False)
    else:
        @pl.when(active)
        def _nc():
            compute(False)


def _kv_lo(qi, block, window, q_offset=0):
    """First k block a banded-causal q block attends (window in tokens).

    ``q_offset`` shifts the q block's global position: the ring's
    cross-chunk hops (parallel/ring_attention.py) reuse these kernels with
    q sitting ``q_offset`` tokens after k, so the band runs diagonally
    through the (q, k) block grid instead of hugging the main diagonal.
    """
    return jnp.maximum(q_offset + qi * block - (window - 1), 0) // block


def _kv_hi(qi, block, q_offset, nk):
    """Last k block with any causally-visible key for this q block."""
    return jnp.minimum((q_offset + qi * block + block - 1) // block, nk - 1)


def _q_lo(kj, block, q_offset):
    """First q block that causally sees a k block (q_offset as above)."""
    return jnp.maximum(kj * block - q_offset, 0) // block


def _q_hi(kj, block, window, q_offset=0):
    """Last q block that attends a banded-causal k block."""
    return (kj * block + block + window - 2 - q_offset) // block


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, block, causal, window=None, softcap=None,
                q_offset=0):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute(masked):
        # matmul inputs stay in the storage dtype (bf16 on the hot path) —
        # the MXU runs bf16 x bf16 -> fp32 at full rate where fp32 x fp32
        # costs several passes; accumulation is fp32 via
        # preferred_element_type, and the softmax math stays fp32.
        q = q_ref[0]  # (BQ, hd)
        kblk = k_ref[0]  # (BK, hd)
        vblk = v_ref[0]
        s, _ = _scores_base2(q, kblk, scale, softcap)  # (BQ, BK)
        if masked:
            q_pos = q_offset + qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
            # INVARIANT (wipe-by-underflow): when window < block, a q-row's
            # first active k-block can be FULLY masked — this tile is then
            # all NEG_INF, so m_new = NEG_INF and p = exp(0) = 1 garbage
            # transiently enters acc/l below. Correctness relies on every
            # q-row's LAST active block holding a live diagonal key, so the
            # later rescale alpha = exp(NEG_INF - m_finite) underflows to
            # exactly 0.0 and wipes the garbage. Changing NEG_INF to a
            # value exp() doesn't flush to zero, or seeding m/l/acc
            # differently, silently breaks banded attention
            # (guard tests: t=384 / window=16 in test_window_attention.py).
            # With q_offset > 0 a row can be dead in EVERY block (the band
            # passed it entirely). Its m then stays NEG_INF through the
            # whole sweep (l accrues exp(0)=1 garbage per masked entry, it
            # does NOT stay 0), so finalize emits lse = m + log(l) ~=
            # NEG_INF and LSE-merging callers fold the garbage `out` away
            # with weight exp(NEG_INF - m_finite) = 0. m, not l, is the
            # dead-row signature.
            s = jnp.where(ok, s, NEG_INF)

        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and window is not None:
        active = (kj <= _kv_hi(qi, block, q_offset, nk)) & (
            kj >= _kv_lo(qi, block, window, q_offset))
    elif causal:
        active = kj <= _kv_hi(qi, block, q_offset, nk)
    else:
        active = kj >= 0
    _dispatch_cells(_compute, qi, kj, block, active, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(kj == nk - 1)
    def _finalize():
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        # max(l, tiny): a dead q BLOCK (no active kj at all, q_offset > 0)
        # reaches here with l = 0 and would emit 0/0 = NaN; dead rows
        # inside an ACTIVE block instead carry l = masked-entry garbage
        # with m = NEG_INF. Both cases emit lse ~= NEG_INF (m + log(l)),
        # which LSE-merging callers weight to exactly zero — `out` for
        # dead rows is garbage by contract, lse is the signal. Live rows
        # have l >= exp2(0) = 1 from their max entry, so values are exact.
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
        # natural-domain lse (m is base-2): API contract for ring merging
        lse_ref[0] = m * INV_LOG2E + jnp.log(l_safe)  # (BQ, 1)


def _flash_fwd(q, k, v, scale, block, causal=True, window=None, softcap=None,
               q_offset=0):
    """q/k/v: (BH, T, hd) -> (out (BH, T, hd), lse (BH, T, 1))."""
    bh, t, hd = q.shape
    nb = t // block
    grid = (bh, nb, nb)
    # causal: masked (above-diagonal) cells clamp their k index to the
    # diagonal so the pipeline never fetches a block the kernel will skip;
    # with a sliding window the stream is clamped from below too. A
    # q_offset>0 block whose whole band misses this k chunk has lo > hi:
    # clip then returns hi (already in [0, nb-1]) as the
    # fetched-but-skipped placeholder index.
    if causal and window is not None:
        kv_spec = pl.BlockSpec(
            (1, block, hd),
            lambda b, i, j: (b, jnp.clip(
                j, _kv_lo(i, block, window, q_offset),
                _kv_hi(i, block, q_offset, nb)), 0))
    elif causal:
        kv_spec = pl.BlockSpec(
            (1, block, hd),
            lambda b, i, j: (b, jnp.minimum(j, _kv_hi(i, block, q_offset,
                                                      nb)), 0))
    else:
        kv_spec = pl.BlockSpec((1, block, hd), lambda b, i, j: (b, j, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block=block,
                          causal=causal, window=window, softcap=softcap,
                          q_offset=q_offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i, j: (b, i, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i, j: (b, i, 0)),
            # (BH, T, 1) rather than (BH, T): Mosaic requires the last two
            # block dims to be (8k, 128k) or equal to the array dims — a
            # trailing singleton satisfies that where a (1, block) tile can't
            pl.BlockSpec((1, block, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, hd), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, hd), jnp.float32),
        ],
        # bh and q-block cells are independent; only the k dimension carries
        # the online-softmax state sequentially
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, block, causal, window=None, softcap=None,
               q_offset=0):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute(masked):
        # bf16 matmul inputs + fp32 accumulate (see _fwd_kernel note);
        # p/ds are computed in fp32 and cast back only to feed the MXU
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0] * LOG2E  # natural -> base-2 (per-row, cheap)
        delta = delta_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        s, t = _scores_base2(q, kblk, scale, softcap)
        if masked:
            q_pos = q_offset + qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
            s = jnp.where(ok, s, NEG_INF)
            # mask p structurally, not via exp underflow: a dead row
            # (q_offset > 0, no live key) has lse ~= NEG_INF, making
            # exp2(NEG_INF - lse) = exp2(~0) = 1 garbage rather than 0
            p = jnp.where(ok, jnp.exp2(s - lse), 0.0)
        else:
            # full cells contain no dead rows (every key is live for every
            # row), so lse is finite and p needs no structural mask
            p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta.astype(jnp.float32))
        if softcap is not None:  # chain through d/ds cap*tanh(s/cap)
            ds = ds * (1.0 - t * t)
        ds = ds * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(kblk.dtype), kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and window is not None:
        active = (kj <= _kv_hi(qi, block, q_offset, nk)) & (
            kj >= _kv_lo(qi, block, window, q_offset))
    elif causal:
        active = kj <= _kv_hi(qi, block, q_offset, nk)
    else:
        active = kj >= 0
    _dispatch_cells(_compute, qi, kj, block, active, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block, causal,
                window=None, softcap=None, q_offset=0):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute(masked):
        # bf16 matmul inputs + fp32 accumulate (see _fwd_kernel note)
        kblk = k_ref[0]  # (BK, hd)
        vblk = v_ref[0]
        q = q_ref[0]  # (BQ, hd)
        do = do_ref[0]
        lse = lse_ref[0] * LOG2E  # natural -> base-2
        delta = delta_ref[0]
        s, t = _scores_base2(q, kblk, scale, softcap)
        if masked:
            q_pos = q_offset + qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
            s = jnp.where(ok, s, NEG_INF)
            # structural masking — see _dq_kernel's dead-row note
            p = jnp.where(ok, jnp.exp2(s - lse), 0.0)
        else:
            p = jnp.exp2(s - lse)  # (BQ, BK); no dead rows in full cells
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta.astype(jnp.float32))
        if softcap is not None:
            ds = ds * (1.0 - t * t)
        ds = ds * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # causal: only q blocks at or below the (offset) diagonal see this k
    # block; a sliding window also bounds how far below
    if causal and window is not None:
        active = (qi >= _q_lo(kj, block, q_offset)) & (
            qi <= _q_hi(kj, block, window, q_offset))
    elif causal:
        active = qi >= _q_lo(kj, block, q_offset)
    else:
        active = qi >= 0
    _dispatch_cells(_compute, qi, kj, block, active, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, block, causal=True, dlse=None,
               window=None, softcap=None, q_offset=0):
    """dlse: optional cotangent for the lse output ((BH, T, 1) fp32).

    The lse gradient folds into the existing kernels for free:
    d lse / d s = p (the softmax row), so a dlse cotangent contributes
    ds += p * dlse — the kernels compute ds = p * (dp - delta), so passing
    delta' = delta - dlse is exactly the combined gradient.
    """
    bh, t, hd = q.shape
    delta = jnp.sum(
        out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # (BH, T, 1), same layout as lse
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    nb = t // block

    # dq: grid (BH, q block, k block), k/v streamed; causal clamps the
    # stream at the diagonal (skipped cells never fetch); a window also
    # clamps from below
    if causal and window is not None:
        # lo > hi (band misses the chunk) resolves to hi via clip — a
        # valid placeholder index; see the fwd kv_spec note
        kv_stream = pl.BlockSpec(
            (1, block, hd),
            lambda b, i, j: (b, jnp.clip(
                j, _kv_lo(i, block, window, q_offset),
                _kv_hi(i, block, q_offset, nb)), 0))
    elif causal:
        kv_stream = pl.BlockSpec(
            (1, block, hd),
            lambda b, i, j: (b, jnp.minimum(j, _kv_hi(i, block, q_offset,
                                                      nb)), 0))
    else:
        kv_stream = pl.BlockSpec((1, block, hd), lambda b, i, j: (b, j, 0))
    q_fixed = pl.BlockSpec((1, block, hd), lambda b, i, j: (b, i, 0))
    vec_fixed = pl.BlockSpec((1, block, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block=block,
                          causal=causal, window=window, softcap=softcap,
                          q_offset=q_offset),
        grid=(bh, nb, nb),
        in_specs=[q_fixed, kv_stream, kv_stream, q_fixed, vec_fixed,
                  vec_fixed],
        out_specs=[q_fixed],
        out_shape=[jax.ShapeDtypeStruct((bh, t, hd), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32)],
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)[0]

    # dk/dv: grid (BH, k block, q block), q/do/lse/delta streamed, clamped
    if causal and window is not None:
        def _q_idx(b, j, i):
            return (b, jnp.clip(jnp.clip(
                i, _q_lo(j, block, q_offset),
                _q_hi(j, block, window, q_offset)), 0, nb - 1), 0)

        q_stream = pl.BlockSpec((1, block, hd), _q_idx)
        vec_stream = pl.BlockSpec((1, block, 1), _q_idx)
    elif causal:
        q_stream = pl.BlockSpec(
            (1, block, hd),
            lambda b, j, i: (b, jnp.maximum(i, _q_lo(j, block, q_offset)), 0))
        vec_stream = pl.BlockSpec(
            (1, block, 1),
            lambda b, j, i: (b, jnp.maximum(i, _q_lo(j, block, q_offset)), 0))
    else:
        q_stream = pl.BlockSpec((1, block, hd), lambda b, j, i: (b, i, 0))
        vec_stream = pl.BlockSpec((1, block, 1), lambda b, j, i: (b, i, 0))
    kv_fixed = pl.BlockSpec((1, block, hd), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block=block,
                          causal=causal, window=window, softcap=softcap,
                          q_offset=q_offset),
        grid=(bh, nb, nb),
        in_specs=[q_stream, kv_fixed, kv_fixed, q_stream, vec_stream,
                  vec_stream],
        out_specs=[kv_fixed, kv_fixed],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, hd), k.dtype),
            jax.ShapeDtypeStruct((bh, t, hd), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, hd), jnp.float32),
            pltpu.VMEM((block, hd), jnp.float32),
        ],
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper in the model's (B, T, H, hd) layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale: float, block: int, window=None, softcap=None):
    out, _ = _flash_fwd(q, k, v, scale, block, window=window, softcap=softcap)
    return out


def _flash_fwd_rule(q, k, v, scale, block, window, softcap):
    out, lse = _flash_fwd(q, k, v, scale, block, window=window, softcap=softcap)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, block, window, softcap, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, scale, block,
                            window=window, softcap=softcap)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_with_lse(q, k, v, scale: float, block: int, causal: bool = True,
                   window: Optional[int] = None,
                   softcap: Optional[float] = None, q_offset: int = 0):
    """(q, k, v) (BH, T, hd) -> (out (BH, T, hd), lse (BH, T, 1) fp32).

    The building block for distributed attention (parallel/ring_attention.py):
    partial results from different K/V chunks merge exactly via their
    log-sum-exp, so a ring hop can run this kernel per chunk and combine —
    differentiable in both outputs (the lse cotangent folds into delta,
    see _flash_bwd).

    ``window``/``softcap`` mirror the square-kernel options; ``q_offset``
    places the q chunk that many tokens after the k chunk (banded ring
    cross-chunk hops). Rows left with no live key under an offset band
    return garbage ``out`` and lse ~= NEG_INF — callers MUST merge by lse
    (the weight underflows to exactly 0), not read ``out`` directly.
    """
    return _flash_fwd(q, k, v, scale, block, causal, window=window,
                      softcap=softcap, q_offset=q_offset)


def _flash_lse_fwd_rule(q, k, v, scale, block, causal, window, softcap,
                        q_offset):
    out, lse = _flash_fwd(q, k, v, scale, block, causal, window=window,
                          softcap=softcap, q_offset=q_offset)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd_rule(scale, block, causal, window, softcap, q_offset,
                        res, cts):
    q, k, v, out, lse = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd(
        q, k, v, out, lse, do, scale, block, causal=causal, dlse=dlse,
        window=window, softcap=softcap, q_offset=q_offset,
    )
    return dq, dk, dv


flash_with_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


# ---------------------------------------------------------------------------
# Native-layout (B, T, D) kernels — no activation transposes
# ---------------------------------------------------------------------------
#
# The square kernels above take (B*H, T, hd): the model's activations are
# (B, T, H*hd), so every call pays a (0, 2, 1, 3) transpose on the way in
# and out — at hd=64 that was the single largest step-time sink left on the
# round-4 trace (~29 ms/step at batch 16; BASELINE.md round-5 plan #1).
# These kernels keep the native layout and make the HEAD a grid dimension:
# grid (B, H/pack, nq, nk) where `pack` sub-heads ride one cell so the lane
# dimension stays at Mosaic's 128 minimum (hd=64 -> 2 heads per cell, which
# also halves the grid and builds the causal mask once per PAIR of heads).
# The kernel bodies are the same online-softmax / lse-delta cells as above,
# re-indexed for the 4D grid. Measured on a TPU v5e chip (batch 16, T=1024,
# GPT-2 dims): fwd+bwd 3.82 ms vs 4.46 ms for kernels+transposes per layer
# call — the win that took the step from MFU 0.47 toward 0.55.


def _btd_pack(h: int, hd: int) -> Optional[int]:
    """Sub-heads per grid cell for the native-layout kernels, or None when
    the (h, hd) combination can't keep the lane dimension at 128."""
    if hd >= 128:
        return 1 if hd % 128 == 0 else None
    if 128 % hd == 0:
        p = 128 // hd
        return p if h % p == 0 else None
    return None


def _fwd_kernel_btd(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                    acc_scr, *, scale, block, hd, pack, window=None,
                    softcap=None):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute(masked):
        q_all = q_ref[0]  # (block, pack*hd)
        k_all = k_ref[0]
        v_all = v_ref[0]
        if masked:
            # causal/band mask built ONCE per cell, shared by all sub-heads
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
        for sh in range(pack):
            lo, hi = sh * hd, (sh + 1) * hd
            q = q_all[:, lo:hi]
            kblk = k_all[:, lo:hi]
            vblk = v_all[:, lo:hi]
            s, _ = _scores_base2(q, kblk, scale, softcap)
            if masked:
                # wipe-by-underflow invariant holds exactly as in
                # _fwd_kernel (q_offset is always 0 here: every q row owns
                # a live diagonal)
                s = jnp.where(ok, s, NEG_INF)
            m = m_scr[sh]
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp2(s - m_new)
            alpha = jnp.exp2(m - m_new)
            m_scr[sh] = m_new
            l_scr[sh] = l_scr[sh] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[sh] = acc_scr[sh] * alpha + jax.lax.dot_general(
                p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    # full/masked cell routing shared with every kernel (_dispatch_cells)
    # — a large cut in a kernel that is VPU-bound, not MXU-bound, at hd=64
    if window is not None:
        active = (kj <= _kv_hi(qi, block, 0, nk)) & (
            kj >= _kv_lo(qi, block, window, 0))
    else:
        active = kj <= _kv_hi(qi, block, 0, nk)
    _dispatch_cells(_compute, qi, kj, block, active, causal=True,
                    window=window)

    @pl.when(kj == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)  # (pack, block, 1)
        o_sub = acc_scr[...] / l_safe  # (pack, block, hd)
        if pack == 1:
            o_ref[0] = o_sub[0].astype(o_ref.dtype)
        else:
            o_ref[0] = jnp.concatenate(
                [o_sub[i] for i in range(pack)], axis=1).astype(o_ref.dtype)
        # natural-domain lse from base-2 m (same contract as _fwd_kernel)
        lse = m_scr[...] * INV_LOG2E + jnp.log(l_safe)  # (pack, block, 1)
        for sh in range(pack):
            lse_ref[0, sh] = lse[sh]


def _dq_kernel_btd(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block, hd, pack, window=None,
                   softcap=None):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute(masked):
        q_all = q_ref[0]
        k_all = k_ref[0]
        v_all = v_ref[0]
        do_all = do_ref[0]
        if masked:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
        for sh in range(pack):
            lo, hi = sh * hd, (sh + 1) * hd
            q = q_all[:, lo:hi]
            kblk = k_all[:, lo:hi]
            vblk = v_all[:, lo:hi]
            do = do_all[:, lo:hi]
            lse = lse_ref[0, sh] * LOG2E  # natural -> base-2
            delta = delta_ref[0, sh]
            s, t = _scores_base2(q, kblk, scale, softcap)
            if masked:
                s = jnp.where(ok, s, NEG_INF)
                p = jnp.where(ok, jnp.exp2(s - lse), 0.0)
            else:
                p = jnp.exp2(s - lse)
            dp = jax.lax.dot_general(
                do, vblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta.astype(jnp.float32))
            if softcap is not None:
                ds = ds * (1.0 - t * t)
            ds = ds * scale
            dq_scr[sh] += jax.lax.dot_general(
                ds.astype(kblk.dtype), kblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if window is not None:
        active = (kj <= _kv_hi(qi, block, 0, nk)) & (
            kj >= _kv_lo(qi, block, window, 0))
    else:
        active = kj <= _kv_hi(qi, block, 0, nk)
    _dispatch_cells(_compute, qi, kj, block, active, causal=True,
                    window=window)

    @pl.when(kj == nk - 1)
    def _finalize():
        if pack == 1:
            dq_ref[0] = dq_scr[0].astype(dq_ref.dtype)
        else:
            dq_ref[0] = jnp.concatenate(
                [dq_scr[i] for i in range(pack)], axis=1).astype(dq_ref.dtype)


def _dkv_kernel_btd(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block, hd,
                    pack, window=None, softcap=None):
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute(masked):
        q_all = q_ref[0]
        k_all = k_ref[0]
        v_all = v_ref[0]
        do_all = do_ref[0]
        if masked:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
        for sh in range(pack):
            lo, hi = sh * hd, (sh + 1) * hd
            q = q_all[:, lo:hi]
            kblk = k_all[:, lo:hi]
            vblk = v_all[:, lo:hi]
            do = do_all[:, lo:hi]
            lse = lse_ref[0, sh] * LOG2E  # natural -> base-2
            delta = delta_ref[0, sh]
            s, t = _scores_base2(q, kblk, scale, softcap)
            if masked:
                s = jnp.where(ok, s, NEG_INF)
                p = jnp.where(ok, jnp.exp2(s - lse), 0.0)
            else:
                p = jnp.exp2(s - lse)
            dv_scr[sh] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, vblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta.astype(jnp.float32))
            if softcap is not None:
                ds = ds * (1.0 - t * t)
            ds = ds * scale
            dk_scr[sh] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    # here the grid streams q per k block: active means qi at or below
    # the diagonal
    if window is not None:
        active = (qi >= _q_lo(kj, block, 0)) & (
            qi <= _q_hi(kj, block, window, 0))
    else:
        active = qi >= _q_lo(kj, block, 0)
    _dispatch_cells(_compute, qi, kj, block, active, causal=True,
                    window=window)

    @pl.when(qi == nq - 1)
    def _finalize():
        if pack == 1:
            dk_ref[0] = dk_scr[0].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[0].astype(dv_ref.dtype)
        else:
            dk_ref[0] = jnp.concatenate(
                [dk_scr[i] for i in range(pack)], axis=1).astype(dk_ref.dtype)
            dv_ref[0] = jnp.concatenate(
                [dv_scr[i] for i in range(pack)], axis=1).astype(dv_ref.dtype)


def _dqkv_kernel_btd(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dk_ref, dv_ref, dq_all_scr, dk_scr, dv_scr,
                     *, scale, block, hd, pack, window=None, softcap=None):
    """FUSED backward: dq + dk + dv in ONE pass over the (kj, qi) grid.

    The split dq / dkv kernels each recompute s, p and dp per active cell
    — 7 matmuls and 2 full VPU softmax chains per cell across the two
    passes, plus double DMA of every q/k/v/do block. Sharing them costs 5
    matmuls and ONE chain: measured on-chip (round 5), the backward is
    VPU-bound at hd=64, so this is the dominant remaining lever.

    Mechanics: grid (B, H/pack, kj, qi) with qi innermost (the dkv
    ordering). dk/dv accumulate per kj in scratch exactly as before. dq
    accumulates across the OUTER kj sweeps into a (nq, pack, block, hd)
    scratch slab indexed by qi — a dynamic index on the leading
    (untiled) dim, plain address arithmetic (unlike the sublane-dim
    dynamic stores Mosaic rejects). Every qi slab is complete by the last
    kj sweep, which writes it out; the dq out-spec index map parks on
    block 0 until that sweep so the buffer stays resident and is flushed
    exactly once per q block with real contents.
    """
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    nk = pl.num_programs(2)

    @pl.when((kj == 0) & (qi == 0))
    def _init_dq_all():
        dq_all_scr[...] = jnp.zeros_like(dq_all_scr)

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute(masked):
        q_all = q_ref[0]
        k_all = k_ref[0]
        v_all = v_ref[0]
        do_all = do_ref[0]
        if masked:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            ok = q_pos >= k_pos
            if window is not None:
                ok = ok & (q_pos - k_pos < window)
        for sh in range(pack):
            lo, hi = sh * hd, (sh + 1) * hd
            q = q_all[:, lo:hi]
            kblk = k_all[:, lo:hi]
            vblk = v_all[:, lo:hi]
            do = do_all[:, lo:hi]
            lse = lse_ref[0, sh] * LOG2E  # natural -> base-2
            delta = delta_ref[0, sh]
            s, t = _scores_base2(q, kblk, scale, softcap)
            if masked:
                s = jnp.where(ok, s, NEG_INF)
                p = jnp.where(ok, jnp.exp2(s - lse), 0.0)
            else:
                p = jnp.exp2(s - lse)
            dv_scr[sh] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, vblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta.astype(jnp.float32))
            if softcap is not None:
                ds = ds * (1.0 - t * t)
            ds = ds * scale
            dk_scr[sh] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dq_all_scr[qi, sh] += jax.lax.dot_general(
                ds.astype(kblk.dtype), kblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if window is not None:
        active = (qi >= _q_lo(kj, block, 0)) & (
            qi <= _q_hi(kj, block, window, 0))
    else:
        active = qi >= _q_lo(kj, block, 0)
    _dispatch_cells(_compute, qi, kj, block, active, causal=True,
                    window=window)

    @pl.when(kj == nk - 1)
    def _emit_dq():
        slab = dq_all_scr[qi]  # (pack, block, hd)
        if pack == 1:
            dq_ref[0] = slab[0].astype(dq_ref.dtype)
        else:
            dq_ref[0] = jnp.concatenate(
                [slab[i] for i in range(pack)], axis=1).astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        if pack == 1:
            dk_ref[0] = dk_scr[0].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[0].astype(dv_ref.dtype)
        else:
            dk_ref[0] = jnp.concatenate(
                [dk_scr[i] for i in range(pack)], axis=1).astype(dk_ref.dtype)
            dv_ref[0] = jnp.concatenate(
                [dv_scr[i] for i in range(pack)], axis=1).astype(dv_ref.dtype)


def _btd_dkv_specs(block, pack, hd, nb, window):
    """Shared BlockSpecs for the (kj, qi)-ordered backward grids: fixed
    k/v blocks per kj, q/do and lse/delta streamed per qi with the band
    clamp — ONE definition for the split dkv kernel and the fused
    dq+dk+dv kernel, so the clamp math cannot diverge."""
    kv_fixed = pl.BlockSpec((1, block, pack * hd),
                            lambda bb, hh, j, i: (bb, j, hh))
    if window is not None:
        def _q_idx(bb, hh, j, i):
            return (bb, jnp.clip(jnp.clip(
                i, _q_lo(j, block, 0), _q_hi(j, block, window, 0)),
                0, nb - 1), hh)

        def _vec_idx(bb, hh, j, i):
            return (bb, hh, jnp.clip(jnp.clip(
                i, _q_lo(j, block, 0), _q_hi(j, block, window, 0)),
                0, nb - 1), 0)
    else:
        def _q_idx(bb, hh, j, i):
            return (bb, jnp.maximum(i, _q_lo(j, block, 0)), hh)

        def _vec_idx(bb, hh, j, i):
            return (bb, hh, jnp.maximum(i, _q_lo(j, block, 0)), 0)
    return (kv_fixed, pl.BlockSpec((1, block, pack * hd), _q_idx),
            pl.BlockSpec((1, pack, block, 1), _vec_idx))


def _flash_fwd_btd(q, k, v, h, scale, block, window=None, softcap=None):
    """q/k/v (B, T, H*hd) -> out (B, T, H*hd), lse (B, H, T, 1) fp32."""
    b, t, d = q.shape
    hd = d // h
    pack = _btd_pack(h, hd)
    nb = t // block
    grid = (b, h // pack, nb, nb)

    if window is not None:
        def kv_idx(bb, hh, i, j):
            return (bb, jnp.clip(j, _kv_lo(i, block, window, 0),
                                 _kv_hi(i, block, 0, nb)), hh)
    else:
        def kv_idx(bb, hh, i, j):
            return (bb, jnp.minimum(j, _kv_hi(i, block, 0, nb)), hh)

    io_spec = pl.BlockSpec((1, block, pack * hd),
                           lambda bb, hh, i, j: (bb, i, hh))
    kv_spec = pl.BlockSpec((1, block, pack * hd), kv_idx)
    # lse layout note (round-5, measured): a (B, H, T, 1) fp32 buffer pads
    # 128x under TPU T(8,128) tiling (trailing singleton -> 128 lanes) —
    # 384 MB of address space per layer at b64, the allocation behind the
    # historic batch>=64 compile failures (tools/exp_b64.py). A dense
    # (B, H, nq, 8, 128) per-q-block plane layout was built and reverted:
    # the (rows, 128) <-> (block, 1) relayout it needs inside the kernels
    # lowers to an unsupported Mosaic gather ("Only 2D gather is
    # supported"), in both the fwd write and bwd read directions. The
    # padding is address space, not DMA traffic (the kernel only writes
    # real lanes), batch is throughput-saturated by 32 on a v5e, and b64
    # runs with remat — so the padded layout stands until Mosaic grows the
    # relayout.
    lse_shape = jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)
    lse_spec = pl.BlockSpec((1, pack, block, 1),
                            lambda bb, hh, i, j: (bb, hh, i, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_btd, scale=scale, block=block, hd=hd,
                          pack=pack, window=window, softcap=softcap),
        grid=grid,
        in_specs=[io_spec, kv_spec, kv_spec],
        out_specs=[io_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct((b, t, d), q.dtype), lse_shape],
        scratch_shapes=[
            pltpu.VMEM((pack, block, 1), jnp.float32),
            pltpu.VMEM((pack, block, 1), jnp.float32),
            pltpu.VMEM((pack, block, hd), jnp.float32),
        ],
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


def _flash_bwd_btd(q, k, v, out, lse, do, h, scale, block, window=None,
                   softcap=None):
    """Native-layout backward: dq, dk, dv in (B, T, H*hd)."""
    b, t, d = q.shape
    hd = d // h
    pack = _btd_pack(h, hd)
    nb = t // block
    # delta = rowsum(out * do) per head: (B, T, H) -> the lse's layout
    # (tiled (B, H, T//128, 128) plane or (B, H, T, 1) — see
    # _flash_fwd_btd). The transpose is on a (B, H, T) fp32 vector —
    # trivial next to the (B, T, D) activation transposes this path exists
    # to kill.
    delta = jnp.sum(
        out.astype(jnp.float32).reshape(b, t, h, hd)
        * do.astype(jnp.float32).reshape(b, t, h, hd), axis=-1)
    delta = delta.transpose(0, 2, 1)[..., None]

    # fused dq+dk+dv kernel (see _dqkv_kernel_btd) whenever its
    # (nq, pack, block, hd) dq scratch stays within a VMEM budget —
    # covers every shipped block_size. OPT-IN (FLASH_FUSED_BWD=1) until
    # validated on real silicon: it is parity-tested in interpret mode,
    # but its dynamic leading-dim scratch indexing has not met Mosaic yet
    # (the r5 tiled-lse layout died on exactly that class of gap), and the
    # tunnel dropped before the A/B could run. bench.py probes it and
    # keeps it only when it compiles AND wins.
    fused = (nb * pack * block * hd * 4 <= 4 * 2**20
             and os.environ.get("FLASH_FUSED_BWD", "0") == "1")
    if fused:
        return _flash_bwd_btd_fused(q, k, v, do, lse, delta, b, t, hd,
                                    pack, nb, scale, block, window, softcap)

    grid = (b, h // pack, nb, nb)
    io_q = pl.BlockSpec((1, block, pack * hd),
                        lambda bb, hh, i, j: (bb, i, hh))
    if window is not None:
        kv_stream = pl.BlockSpec(
            (1, block, pack * hd),
            lambda bb, hh, i, j: (bb, jnp.clip(
                j, _kv_lo(i, block, window, 0), _kv_hi(i, block, 0, nb)),
                hh))
    else:
        kv_stream = pl.BlockSpec(
            (1, block, pack * hd),
            lambda bb, hh, i, j: (bb, jnp.minimum(
                j, _kv_hi(i, block, 0, nb)), hh))
    vec_q = pl.BlockSpec((1, pack, block, 1),
                         lambda bb, hh, i, j: (bb, hh, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_btd, scale=scale, block=block, hd=hd,
                          pack=pack, window=window, softcap=softcap),
        grid=grid,
        in_specs=[io_q, kv_stream, kv_stream, io_q, vec_q, vec_q],
        out_specs=[io_q],
        out_shape=[jax.ShapeDtypeStruct((b, t, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((pack, block, hd), jnp.float32)],
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)[0]

    kv_fixed, q_stream, vec_stream = _btd_dkv_specs(
        block, pack, hd, nb, window)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_btd, scale=scale, block=block, hd=hd,
                          pack=pack, window=window, softcap=softcap),
        grid=grid,
        in_specs=[q_stream, kv_fixed, kv_fixed, q_stream, vec_stream,
                  vec_stream],
        out_specs=[kv_fixed, kv_fixed],
        out_shape=[jax.ShapeDtypeStruct((b, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, t, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((pack, block, hd), jnp.float32),
                        pltpu.VMEM((pack, block, hd), jnp.float32)],
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _flash_bwd_btd_fused(q, k, v, do, lse, delta, b, t, hd, pack, nb,
                         scale, block, window, softcap):
    """One fused pallas_call for dq+dk+dv — see _dqkv_kernel_btd."""
    d = q.shape[2]
    grid = (b, d // (pack * hd), nb, nb)
    kv_fixed, q_stream, vec_stream = _btd_dkv_specs(
        block, pack, hd, nb, window)
    # dq out: park on block 0 until the last kj sweep (when every qi slab
    # is complete) so the buffer is flushed exactly once per q block with
    # real contents — see _dqkv_kernel_btd's docstring
    dq_spec = pl.BlockSpec(
        (1, block, pack * hd),
        lambda bb, hh, j, i: (bb, jnp.where(j == nb - 1, i, 0), hh))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_dqkv_kernel_btd, scale=scale, block=block,
                          hd=hd, pack=pack, window=window, softcap=softcap),
        grid=grid,
        in_specs=[q_stream, kv_fixed, kv_fixed, q_stream, vec_stream,
                  vec_stream],
        out_specs=[dq_spec, kv_fixed, kv_fixed],
        out_shape=[jax.ShapeDtypeStruct((b, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, t, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((nb, pack, block, hd), jnp.float32),
                        pltpu.VMEM((pack, block, hd), jnp.float32),
                        pltpu.VMEM((pack, block, hd), jnp.float32)],
        # kj and qi share the dq scratch slab and the parked dq out block:
        # a megacore split over either would break that residency
        compiler_params=compat.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_btd(q, k, v, h: int, scale: float, block: int, window=None,
               softcap=None):
    out, _ = _flash_fwd_btd(q, k, v, h, scale, block, window=window,
                            softcap=softcap)
    return out


def _flash_btd_fwd_rule(q, k, v, h, scale, block, window, softcap):
    out, lse = _flash_fwd_btd(q, k, v, h, scale, block, window=window,
                              softcap=softcap)
    return out, (q, k, v, out, lse)


def _flash_btd_bwd_rule(h, scale, block, window, softcap, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_btd(q, k, v, out, lse, do, h, scale, block,
                          window=window, softcap=softcap)


_flash_btd.defvjp(_flash_btd_fwd_rule, _flash_btd_bwd_rule)


def causal_attention(
    q: jax.Array,  # (B, T, H, hd)
    k: jax.Array,  # (B, S, KV, hd)
    v: jax.Array,
    *,
    attn_pdrop: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    kv_offset: int | jax.Array = 0,
    window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Drop-in for ops.attention.causal_attention, flash-accelerated.

    Falls back to the einsum oracle whenever the kernel doesn't apply:
    attention dropout active, decode-style q/k length mismatch, or T not
    tileable. The fallback IS the definition of correctness; the kernel is
    tested for parity against it. ``window`` enables sliding-window
    (banded) attention — the kernel skips and never fetches blocks outside
    the band, so compute scales with T*window instead of T^2.
    """
    b, t, h, hd = q.shape
    s = k.shape[1]
    block = _block_sizes(t)
    use_flash = (
        block is not None
        and t == s
        and (deterministic or attn_pdrop == 0.0)
        and isinstance(kv_offset, int)
        and kv_offset == 0
    )
    if not use_flash:
        # the fallback is silent perf loss on the training path (VERDICT r1
        # weak #3) — warn once when a large training-shaped call degrades
        if t == s and t > 512 and not _interpret():
            import warnings

            warnings.warn(
                f"flash attention fell back to the einsum oracle for T={t} "
                f"(block not tileable or dropout active): O(T^2) HBM "
                f"scores will be materialised",
                stacklevel=2,
            )
        return attn_ops.causal_attention(
            q, k, v, attn_pdrop=attn_pdrop, dropout_key=dropout_key,
            deterministic=deterministic, kv_offset=kv_offset, window=window,
            logit_softcap=logit_softcap,
        )
    kv = k.shape[2]
    k = attn_ops.repeat_kv(k, h // kv)
    v = attn_ops.repeat_kv(v, h // kv)
    scale = 1.0 / math.sqrt(hd)
    win = None if window is None else int(window)
    cap = None if logit_softcap is None else float(logit_softcap)
    # Native-layout path: the model's activations are (B, T, H*hd) under
    # the hood, so the reshape below is free where to_bh pays two real
    # transposes per call (the round-4 trace's biggest remaining sink).
    # FLASH_LAYOUT=bh forces the transpose path (bench A/B escape hatch).
    if (os.environ.get("FLASH_LAYOUT", "auto") != "bh"
            and _btd_applies(h, hd)):
        if _btd_pack(h, hd) is not None:
            out2 = _flash_btd(
                q.reshape(b, t, h * hd), k.reshape(b, t, h * hd),
                v.reshape(b, t, h * hd), h, scale, block, win, cap)
            return out2.reshape(b, t, h, hd)
        else:
            # Odd head counts (gpt2-xl's 25) can't pair sub-heads evenly;
            # pad with zero heads up to the pack unit and slice the
            # result. A zero head attends uniformly over zero values —
            # finite lse, zero output and zero gradients, all discarded
            # by the slice (its VJP zero-pads the cotangent). Costs
            # (hp-h)/h extra kernel work (4% at h=25) against the two
            # transposes saved.
            unit = 128 // hd
            hp = -(-h // unit) * unit
            zpad = jnp.zeros((b, t, (hp - h) * hd), q.dtype)
            out2 = _flash_btd(
                jnp.concatenate([q.reshape(b, t, h * hd), zpad], axis=-1),
                jnp.concatenate([k.reshape(b, t, h * hd), zpad], axis=-1),
                jnp.concatenate([v.reshape(b, t, h * hd), zpad], axis=-1),
                hp, scale, block, win, cap)
            return out2[..., :h * hd].reshape(b, t, h, hd)
    # (B, T, H, hd) -> (B*H, T, hd)
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    out = _flash(to_bh(q), to_bh(k), to_bh(v), scale, block, win, cap)
    return out.reshape(b, h, t, hd).transpose(0, 2, 1, 3)
