"""Causal flash attention — Pallas TPU kernel (FlashAttention-2 style).

Replaces, on the hot path, the einsum oracle in ops/attention.py (itself the
intended semantics of the reference's fused torch attention,
/root/reference/mingpt/model.py:147-165): same math, different memory story.
The einsum path materialises the (B, H, T, S) logits in HBM; this kernel
streams K/V blocks through VMEM with an online softmax, so attention memory
is O(T·d) — the property that makes long block_size HBM-feasible
(SURVEY §5.7's prescription for this framework).

Shapes follow ops.attention.causal_attention: q (B, T, H, hd), k/v
(B, S, KV, hd) with GQA handled by broadcasting outside the kernel (autodiff
then sums dk/dv over the query-head group for free).

Forward: grid (B*H, T/BQ); each cell loads its q block, loops over k blocks
up to the diagonal (causal), maintaining running max m, denominator l and
accumulator acc; also emits the log-sum-exp per row for the backward.
Backward: two kernels (dq over q blocks; dk/dv over k blocks) recompute the
probabilities from the saved LSE — no stored attention matrix anywhere.

Falls back to the einsum oracle when the shape/config doesn't fit the kernel
(attention dropout on, decode-time cross lengths, T not a multiple of the
block) — correctness is never gated on the fast path. On CPU the kernel runs
in Pallas interpret mode, which is how the parity tests exercise it.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from mingpt_distributed_tpu.ops import attention as attn_ops

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(t: int) -> Optional[int]:
    """Pick a square block size dividing T, or None if the kernel won't fit."""
    for b in (512, 256, 128):
        if t % b == 0:
            return b
    if t <= 128 and t % 8 == 0:
        return t
    return None


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block, t):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, hd)
    hd = q.shape[-1]

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block, block), :]
        vblk = v_ref[0, pl.ds(kb * block, block), :]
        s = jax.lax.dot_general(
            q, kblk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
        k_pos = kb * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((block, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    acc0 = jnp.zeros((block, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, qi + 1, body, (m0, l0, acc0))

    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, scale, block):
    """q/k/v: (BH, T, hd) -> (out (BH, T, hd), lse (BH, T))."""
    bh, t, hd = q.shape
    grid = (bh, t // block)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block=block, t=t),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, hd), q.dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, scale, block, t):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    hd = q.shape[-1]

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * scale, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
        k_pos = kb * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        0, qi + 1, body, jnp.zeros((block, hd), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, block, t):
    kb = pl.program_id(1)
    nq = t // block
    kblk = k_ref[0].astype(jnp.float32)  # (BK, hd)
    vblk = v_ref[0].astype(jnp.float32)
    hd = kblk.shape[-1]

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block, block)][:, None]
        delta = delta_ref[0, pl.ds(qb * block, block)][:, None]
        s = jax.lax.dot_general(
            q * scale, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        q_pos = qb * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
        k_pos = kb * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # (BQ, BK)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    # only q blocks at or below the diagonal see this k block
    dk0 = jnp.zeros((block, hd), jnp.float32)
    dv0 = jnp.zeros((block, hd), jnp.float32)
    dk, dv = jax.lax.fori_loop(kb, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, block):
    bh, t, hd = q.shape
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    grid = (bh, t // block)
    qspec_blk = pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0))
    qspec_full = pl.BlockSpec((1, t, hd), lambda b, i: (b, 0, 0))
    vec_blk = pl.BlockSpec((1, block), lambda b, i: (b, i))
    vec_full = pl.BlockSpec((1, t), lambda b, i: (b, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block=block, t=t),
        grid=grid,
        in_specs=[qspec_blk, qspec_full, qspec_full, qspec_blk, vec_blk, vec_blk],
        out_specs=[qspec_blk],
        out_shape=[jax.ShapeDtypeStruct((bh, t, hd), q.dtype)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)[0]

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block=block, t=t),
        grid=grid,
        in_specs=[qspec_full, qspec_blk, qspec_blk, qspec_full, vec_full, vec_full],
        out_specs=[qspec_blk, qspec_blk],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, hd), k.dtype),
            jax.ShapeDtypeStruct((bh, t, hd), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper in the model's (B, T, H, hd) layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale: float, block: int):
    out, _ = _flash_fwd(q, k, v, scale, block)
    return out


def _flash_fwd_rule(q, k, v, scale, block):
    out, lse = _flash_fwd(q, k, v, scale, block)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, block, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, scale, block)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def causal_attention(
    q: jax.Array,  # (B, T, H, hd)
    k: jax.Array,  # (B, S, KV, hd)
    v: jax.Array,
    *,
    attn_pdrop: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Drop-in for ops.attention.causal_attention, flash-accelerated.

    Falls back to the einsum oracle whenever the kernel doesn't apply:
    attention dropout active, decode-style q/k length mismatch, or T not
    tileable. The fallback IS the definition of correctness; the kernel is
    tested for parity against it.
    """
    b, t, h, hd = q.shape
    s = k.shape[1]
    block = _block_sizes(t)
    use_flash = (
        block is not None
        and t == s
        and (deterministic or attn_pdrop == 0.0)
        and isinstance(kv_offset, int)
        and kv_offset == 0
    )
    if not use_flash:
        return attn_ops.causal_attention(
            q, k, v, attn_pdrop=attn_pdrop, dropout_key=dropout_key,
            deterministic=deterministic, kv_offset=kv_offset,
        )
    kv = k.shape[2]
    k = attn_ops.repeat_kv(k, h // kv)
    v = attn_ops.repeat_kv(v, h // kv)
    scale = 1.0 / math.sqrt(hd)
    # (B, T, H, hd) -> (B*H, T, hd)
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    out = _flash(to_bh(q), to_bh(k), to_bh(v), scale, block)
    return out.reshape(b, h, t, hd).transpose(0, 2, 1, 3)
