"""Mixture-of-experts MLP with capacity-based dispatch (GShard/Switch style).

Beyond-parity capability (SURVEY §2.2: the reference has a dense MLP only,
model.py:179-184; EP/MoE marked absent). TPU-native design: dispatch and
combine are dense einsums against a static-shape one-hot tensor — no dynamic
shapes, no host control flow — so the whole layer jits into one XLA program.
Expert weights carry a leading expert axis that shards over the mesh's ``ep``
axis (parallel/mesh.py PARAM_RULES); since the token axis is batch-sharded
over dp/fsdp/ep, the dispatch einsum contracts a token-sharded tensor against
expert-sharded weights and **GSPMD inserts the all-to-alls** — the
hand-written NCCL alltoall of GPU MoE stacks becomes a compiler decision
(the framework's ICI/DCN story, SURVEY §2.3).

Tokens are routed in fixed-size **groups** (GShard's trick): the one-hot
dispatch tensor is (G, group, E, cap_per_group), so its memory is
k·factor·group·S — *linear* in sequence length — instead of the k·factor·S²
a single global group would cost (which at block_size 8192 would be GBs per
layer). Capacity is per group; cross-group imbalance can drop slightly more
tokens than global routing, the standard trade-off.

Routing: softmax router, top-k. k=1 (Switch) scales expert output by the
raw router probability — required so the router receives task-loss gradient
(with renormalised gates the k=1 weight is identically 1 and d loss/d router
== 0). k>=2 (GShard) renormalises the chosen gates to sum to 1. Tokens
overflowing an expert's per-group capacity are dropped for that slot (their
residual path still carries them). Load-balancing aux loss is the
Switch-Transformer one: E · Σ_e f_e · P_e over all tokens.

Caveat: when capacity binds, which tokens drop depends on the *set* of
tokens evaluated together — so KV-cached decode (one token at a time) only
reproduces a full re-forward when capacity_factor is high enough that
nothing drops (factor >= E/k guarantees it). Training is unaffected.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

# Max tokens routed as one group; actual group size is the largest divisor
# of S at most this (S itself for small inputs). Groups below MIN_GROUP
# would collapse per-group expert capacity toward 1 and silently drop most
# routes — if S has no divisor in [MIN_GROUP, MAX_GROUP], route it as one
# big group instead (more dispatch memory, correct routing).
MAX_GROUP = 1024
MIN_GROUP = 128


def _group_size(s: int) -> int:
    if s <= MAX_GROUP:
        return s
    for g in range(MAX_GROUP, MIN_GROUP - 1, -1):
        if s % g == 0:
            return g
    return s


def _route_group(probs, *, top_k: int, cap: int):
    """One group's dispatch/combine from (gs, E) router probs.

    Returns (dispatch (gs, E, cap), combine (gs, E, cap), top1 (gs, E))."""
    gs, e = probs.shape
    remaining = probs
    counts = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros((gs, e, cap), jnp.float32)
    combine = jnp.zeros((gs, e, cap), jnp.float32)
    gates, onehots = [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)            # (gs,)
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (gs, E)
        gates.append(jnp.sum(probs * oh, axis=-1))      # true prob, not masked
        onehots.append(oh)
        remaining = remaining * (1.0 - oh)
    # k=1: scale by the raw prob (Switch) so the router gets task gradient;
    # k>1: renormalise over the chosen k (GShard)
    denom = sum(gates) if top_k > 1 else jnp.ones_like(gates[0])
    for g, oh in zip(gates, onehots):
        # position of each token within its expert's buffer, honouring
        # tokens already placed by earlier slots
        pos = counts[None, :] + jnp.cumsum(oh, axis=0) - oh   # (gs, E)
        keep = oh * (pos < cap)
        counts = counts + jnp.sum(keep, axis=0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
        sel = keep[..., None] * slot                     # (gs, E, cap)
        dispatch = dispatch + sel
        combine = combine + sel * (g / jnp.maximum(denom, 1e-9))[:, None, None]
    return dispatch, combine, onehots[0]


def moe_mlp(
    x: jax.Array,        # (B, T, D) — post-norm activations
    w_router: jax.Array,  # (D, E)
    w_e1: jax.Array,      # (E, D, F) — E/ep local experts under ep_axis
    w_e2: jax.Array,      # (E, F, D)
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    w_gate: jax.Array = None,  # (E, D, F): SwiGLU experts (Mixtral-style)
    ep_axis: str = None,
) -> Tuple[jax.Array, jax.Array]:
    """Expert-routed MLP: GELU experts, or SwiGLU when ``w_gate`` is given
    (h = silu(x·w_gate) * (x·w_e1), Mixtral-style). Returns
    (out (B, T, D), aux_loss scalar).

    ``ep_axis``: manual expert parallelism for shard_map regions (the
    pipeline — models/gpt.py), where GSPMD can't insert the all-to-alls
    itself. ``x`` is this shard's tokens, ``w_e*`` hold E/ep local experts
    (expert dim sharded by PARAM_RULES), ``w_router`` is replicated with
    all E columns. Routing runs locally against ALL experts; the expert
    FFN is redistributed with two all_to_alls over ``ep_axis`` — the same
    exchange GSPMD derives for the sharded einsum in the non-manual path.
    The aux loss stays a per-shard statistic either way; callers average
    it over the batch-ish axes (pipeline.py pmean includes ep).
    """
    b, t, d = x.shape
    e = w_e1.shape[0]
    ep = 1
    if ep_axis is not None:
        ep = jax.lax.psum(1, ep_axis)
        e = e * ep  # e: GLOBAL expert count; w_e* hold e/ep local rows
    if w_router.shape[1] != e:
        raise ValueError(
            f"router has {w_router.shape[1]} experts, weights imply {e}"
        )
    s = b * t
    gs = _group_size(s)
    ng = s // gs
    xs = x.reshape(ng, gs, d)

    logits = jnp.einsum(
        "gsd,de->gse", xs.astype(jnp.float32), w_router.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # (G, gs, E) fp32

    cap = max(1, math.ceil(top_k * gs / e * capacity_factor))
    dispatch, combine, top1 = jax.vmap(
        lambda p: _route_group(p, top_k=top_k, cap=cap)
    )(probs)  # (G, gs, E, cap) x2, (G, gs, E)

    # (G, gs, E, cap) x (G, gs, D) -> experts see (E, G*cap, D)
    expert_in = jnp.einsum("gsec,gsd->gecd", dispatch.astype(x.dtype), xs)
    expert_in = expert_in.transpose(1, 0, 2, 3).reshape(e, ng * cap, d)
    if ep_axis is not None:
        # exchange: every shard sends each peer the inputs it routed to
        # that peer's experts, receiving its own experts' tokens from all
        # peers -> (E/ep, ep*n, d); shard i holds global experts
        # [i*E/ep, (i+1)*E/ep) exactly as PARAM_RULES lays them out
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )
    up = jnp.einsum(
        "end,edf->enf", expert_in, w_e1.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    if w_gate is not None:
        gate = jnp.einsum(
            "end,edf->enf", expert_in, w_gate.astype(x.dtype),
            preferred_element_type=jnp.float32,
        )
        h = (jax.nn.silu(gate) * up).astype(x.dtype)
    else:
        h = jax.nn.gelu(up).astype(x.dtype)
    expert_out = jnp.einsum(
        "enf,efd->end", h, w_e2.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )  # (E, G*cap, D) fp32 — (E/ep, ep*n, D) under ep_axis
    if ep_axis is not None:
        # inverse exchange: outputs return to the shards whose tokens they
        # are -> (E, n, d) with the global expert axis restored
        expert_out = jax.lax.all_to_all(
            expert_out, ep_axis, split_axis=1, concat_axis=0, tiled=True
        )
    expert_out = expert_out.reshape(e, ng, cap, d).transpose(1, 0, 2, 3)
    out = jnp.einsum(
        "gsec,gecd->gsd", combine.astype(jnp.float32), expert_out
    ).astype(x.dtype)

    # Switch load-balancing loss on top-1 assignment, over all tokens
    f = jnp.mean(top1.reshape(s, e), axis=0)   # fraction routed per expert
    p = jnp.mean(probs.reshape(s, e), axis=0)  # mean router prob per expert
    aux = e * jnp.sum(f * p)
    return out.reshape(b, t, d), aux
