"""Mixture-of-experts MLP with capacity-based dispatch (GShard/Switch style).

Beyond-parity capability (SURVEY §2.2: the reference has a dense MLP only,
model.py:179-184; EP/MoE marked absent). TPU-native design: dispatch and
combine are dense einsums against a static-shape (tokens, experts, capacity)
one-hot tensor — no dynamic shapes, no host control flow — so the whole layer
jits into one XLA program. Expert weights carry a leading expert axis that
shards over the mesh's ``ep`` axis (parallel/mesh.py PARAM_RULES); since the
token axis is batch-sharded over dp/fsdp/ep, the dispatch einsum contracts a
token-sharded tensor against expert-sharded weights and **GSPMD inserts the
all-to-alls** — the hand-written NCCL alltoall of GPU MoE stacks becomes a
compiler decision (the framework's ICI/DCN story, SURVEY §2.3).

Routing: softmax router, top-k (k=1 Switch, k=2 GShard default), gates
renormalised over the chosen k. Capacity C = ceil(k·S/E · capacity_factor);
tokens overflowing an expert's capacity are dropped for that slot (their
residual path still carries them — standard behaviour). Load-balancing aux
loss is the Switch-Transformer one: E · Σ_e f_e · P_e, where f_e is the
fraction of tokens whose top-1 choice is e and P_e the mean router prob.

Caveat: when capacity binds, which tokens drop depends on the *set* of
tokens evaluated together — so KV-cached decode (one token at a time) only
reproduces a full re-forward when capacity_factor is high enough that
nothing drops (factor >= E/k guarantees it). Training is unaffected.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def moe_mlp(
    x: jax.Array,        # (B, T, D) — post-norm activations
    w_router: jax.Array,  # (D, E)
    w_e1: jax.Array,      # (E, D, F)
    w_e2: jax.Array,      # (E, F, D)
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """Expert-routed GELU MLP. Returns (out (B, T, D), aux_loss scalar)."""
    b, t, d = x.shape
    e = w_e1.shape[0]
    s = b * t
    xs = x.reshape(s, d)

    logits = jnp.einsum(
        "sd,de->se", xs.astype(jnp.float32), w_router.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # (S, E) fp32

    cap = max(1, math.ceil(top_k * s / e * capacity_factor))

    # top-k routing with running per-expert position counters
    remaining = probs
    counts = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros((s, e, cap), jnp.float32)
    combine = jnp.zeros((s, e, cap), jnp.float32)
    gates, onehots = [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)            # (S,)
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (S, E)
        gates.append(jnp.sum(probs * oh, axis=-1))      # true prob, not masked
        onehots.append(oh)
        remaining = remaining * (1.0 - oh)
    denom = sum(gates)
    for g, oh in zip(gates, onehots):
        # position of each token within its expert's buffer, honouring
        # tokens already placed by earlier slots
        pos = counts[None, :] + jnp.cumsum(oh, axis=0) - oh   # (S, E)
        keep = oh * (pos < cap)
        counts = counts + jnp.sum(keep, axis=0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
        sel = keep[..., None] * slot                     # (S, E, C)
        dispatch = dispatch + sel
        combine = combine + sel * (g / jnp.maximum(denom, 1e-9))[:, None, None]

    expert_in = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), xs)
    h = jax.nn.gelu(jnp.einsum(
        "ecd,edf->ecf", expert_in, w_e1.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )).astype(x.dtype)
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, w_e2.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    out = jnp.einsum(
        "sec,ecd->sd", combine.astype(jnp.float32), expert_out
    ).astype(x.dtype)

    # Switch load-balancing loss on top-1 assignment
    f = jnp.mean(onehots[0], axis=0)      # fraction routed to each expert
    p = jnp.mean(probs, axis=0)           # mean router prob per expert
    aux = e * jnp.sum(f * p)
    return out.reshape(b, t, d), aux
