"""Elementwise / normalisation / MLP building blocks.

TPU-native replacements for the torch.nn modules the reference composes
(/root/reference/mingpt/model.py:171-231): pure functions over arrays, mixed
precision by construction — normalisations and softmax in float32, matmuls in
the configured compute dtype (bfloat16 on the MXU) — and everything traceable
under jit so XLA fuses the elementwise chains into the surrounding matmuls.

The MLP here is the *intended* reference MLP — Linear -> GELU -> Linear ->
Dropout (upstream minGPT, reference README.md:99). The reference as shipped
ordered it Linear -> Linear -> GELU (bug B5, model.py:179-184), collapsing to
a single linear map; that bug is deliberately not reproduced.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5) -> jax.Array:
    """LayerNorm in float32 regardless of input dtype (TPU numerics rule)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(dtype)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm (Llama-retrofit toggle, BASELINE config #5)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    y = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (y * scale).astype(dtype)


def gelu(x: jax.Array) -> jax.Array:
    """tanh-approximate GELU — the GPT-2 variant (HF ``gelu_new``), so
    from_pretrained logits match the OpenAI weights bit-for-bit-ish."""
    return jax.nn.gelu(x, approximate=True)


def dropout(
    x: jax.Array, rate: float, key: Optional[jax.Array], deterministic: bool
) -> jax.Array:
    """Inverted dropout; identity when deterministic or rate == 0."""
    if deterministic or rate == 0.0:
        return x
    assert key is not None, "dropout in train mode needs a PRNG key"
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def dense(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None) -> jax.Array:
    """x @ w (+ b) with the matmul in x's compute dtype (bf16 on the MXU)."""
    y = x @ w.astype(x.dtype)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def mlp_gelu(
    x: jax.Array,
    w_fc: jax.Array,
    b_fc: Optional[jax.Array],
    w_proj: jax.Array,
    b_proj: Optional[jax.Array],
) -> jax.Array:
    """The transformer MLP: fc -> GELU -> proj (correct B5 ordering)."""
    return dense(gelu(dense(x, w_fc, b_fc)), w_proj, b_proj)


def mlp_swiglu(
    x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array
) -> jax.Array:
    """SwiGLU MLP (Llama retrofit): down(silu(gate(x)) * up(x))."""
    return dense(jax.nn.silu(dense(x, w_gate)) * dense(x, w_up), w_down)
