"""Multi-head causal self-attention — the einsum reference implementation.

Replaces the reference's delegation to torch's fused ``nn.MultiheadAttention``
(/root/reference/mingpt/model.py:147-165). Two deliberate departures:

* **Correct causal masking.** The reference registered a float tril-of-ones
  and passed it as an additive attention mask, which *fails to mask* future
  positions (bug B6, model.py:142-145,164). Here causality is a boolean
  ``query >= key`` comparison materialised lazily inside the kernel — XLA
  fuses it into the softmax; no (T, T) buffer is stored per layer.
* **No fused-QKV opacity.** q/k/v are explicit arrays shaped
  ``(batch, seq, heads, head_dim)``, supporting grouped-query attention
  (n_kv_head < n_head) and RoPE for the Llama retrofit.

This einsum path is the *oracle*: the Pallas flash-attention kernel
(ops/flash_attention.py) and the ring-attention path (parallel/ring_attention.py)
are tested for parity against it.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-finite instead of -inf: keeps softmax NaN-free in bf16


def softcap(x: jax.Array, cap: Optional[float]) -> jax.Array:
    """Gemma-2-style logit soft-capping: cap * tanh(x / cap); identity when
    cap is None/0. One definition shared by the oracle, the LM-head paths
    and decode (the Pallas kernels inline it — kernel code can't call out)."""
    if not cap:
        return x
    return cap * jnp.tanh(x / cap)


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention: (B,S,KV,hd)->(B,S,KV*rep,hd)."""
    if n_rep == 1:
        return k
    b, s, kv, hd = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kv, n_rep, hd)).reshape(
        b, s, kv * n_rep, hd
    )


def causal_attention(
    q: jax.Array,  # (B, T, H, hd)
    k: jax.Array,  # (B, S, KV, hd)
    v: jax.Array,  # (B, S, KV, hd)
    *,
    attn_pdrop: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    kv_offset: int | jax.Array = 0,
    window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Causal scaled-dot-product attention, softmax in float32.

    ``kv_offset`` is the absolute position of q[0] relative to k[0] — 0 for
    training (S == T, self-attention), the cache length during incremental
    decoding (so a single query attends to all cached keys).
    ``window`` enables sliding-window (banded) attention: each query sees
    only the last ``window`` positions, itself included (Mistral-style;
    ``None`` = full causal). ``logit_softcap`` applies Gemma-2-style
    ``cap * tanh(logits / cap)`` to the scores before masking.
    Returns (B, T, H, hd) in q's dtype.
    """
    b, t, h, hd = q.shape
    kv = k.shape[2]
    k = repeat_kv(k, h // kv)
    v = repeat_kv(v, h // kv)

    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, dtype=jnp.float32))
    # (B, H, T, S) logits in float32
    logits = jnp.einsum(
        "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
    ) * scale
    logits = softcap(logits, logit_softcap)

    s = k.shape[1]
    q_pos = jnp.arange(t)[:, None] + kv_offset  # absolute query positions
    k_pos = jnp.arange(s)[None, :]
    allowed = q_pos >= k_pos  # (T, S) boolean — the B6 fix
    if window is not None:
        allowed = allowed & (q_pos - k_pos < window)
    logits = jnp.where(allowed[None, None], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    if not deterministic and attn_pdrop > 0.0:
        assert dropout_key is not None
        keep = 1.0 - attn_pdrop
        mask = jax.random.bernoulli(dropout_key, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0)

    out = jnp.einsum(
        "bhts,bshd->bthd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def rope_tables(
    positions: jax.Array, head_dim: int, theta: float = 10000.0
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embeddings at the given absolute positions.

    Returns (P, head_dim/2) float32 each, split-half (rotate-half) convention.
    """
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate (B, T, H, hd) by per-position tables (T, hd/2)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[None, :, None, :].astype(jnp.float32)
    sin = sin[None, :, None, :].astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1
    )
    return out.astype(x.dtype)
