"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Beyond-parity strategy (SURVEY §2.2 marks PP "absent" in the reference —
blocks run as one nn.Sequential on one device, model.py:245-246). TPU-native
design: the stacked-layer axis of the block parameters (models/gpt.py stacks
all layers along a leading axis for ``lax.scan``) is *sharded* over ``pp``
— each stage holds n_layer/pp contiguous layers — and activations flow
stage-to-stage with ``lax.ppermute`` (point-to-point neighbour exchange, the
cheapest collective: rides a single ICI/DCN link per hop).

Schedule: classic GPipe. The local batch is split into M microbatches; the
loop runs M + pp - 1 ticks. At tick t, stage 0 ingests microbatch t, every
stage applies its layer stack to the microbatch it currently holds, stage
pp-1 banks its finished microbatch (t - pp + 1), and activations rotate one
hop. Bubble fraction (pp-1)/(M+pp-1) — raise ``cfg.pp_microbatches`` to
amortise. The whole schedule is one ``lax.scan`` inside one ``shard_map``,
so it is reverse-differentiable as-is: autodiff transposes ppermute into the
reverse hop and the backward pass runs the mirror-image pipeline.

Why GPipe (+ remat) and not 1F1B: 1F1B's advantage over GPipe is live
activation memory — O(pp) in-flight microbatches instead of O(M) — at the
cost of hand-orchestrating interleaved forward/backward (a custom_vjp over
the whole schedule; autodiff can no longer derive the backward pipeline).
Under XLA the same memory bound comes from ``cfg.remat``: per-tick
activations are rematerialised in the transposed scan, so stored state is
one activation per microbatch boundary, while the schedule stays a plain
differentiable scan the compiler can fuse. Same bubble fraction either way.

Composition:
- pp x dp/fsdp: batch stays sharded over BATCH_AXES inside the region.
- pp x sp (``seq_sharded=True``): activations stay sequence-sharded inside
  the region too; the caller's ``apply_stack`` runs sequence-parallel
  attention (ring / Ulysses per-shard bodies over the ``sp`` axis — legal
  here because the pipeline's shard_map already manualises every mesh axis).
- pp x MoE: ``apply_stack`` returns a per-stage aux (load-balancing) loss;
  garbage warm-up/drain ticks are masked out, stages sum over ``pp`` and the
  batch-ish axes average, reproducing the single-device aux semantics.
  (Expert weights are gathered at stage entry like the rest of the stage's
  params — ZeRO-style JIT gather — so combine pp with ep=1.)
- Layer-granular tensor parallelism inside a stage is not composed here
  (entering the manual region gathers each stage's params over fsdp/tp).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES


def pipeline_blocks(
    x: jax.Array,              # (B, T, D) activations (batch-sharded outside)
    xs: Any,                   # scanned-over pytree, leading global layer axis
    consts: Any,               # replicated extras (e.g. rope tables), pytree
    apply_stack: Callable[[jax.Array, Any, Any, jax.Array], Tuple[jax.Array, jax.Array]],
    mesh: Mesh,
    *,
    n_microbatches: int = 0,
    seq_sharded: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Apply all layers to ``x`` across pipeline stages.

    ``apply_stack(x_mb, xs_local, consts, mb_idx) -> (y_mb, aux)`` applies
    one stage's local layer stack (n_layer/pp layers) to one microbatch and
    returns its scalar aux loss (0 for dense MLPs); ``mb_idx`` is the index
    of the microbatch being processed (fold it into any PRNG keys so
    stochastic ops like dropout decorrelate across microbatches).
    ``seq_sharded`` keeps the sequence dim sharded over ``sp`` inside the
    region (apply_stack must then run sequence-parallel attention).
    Returns (activations, aux) — semantically equivalent to scanning the
    full layer axis on one device.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        return apply_stack(x, xs, consts, jnp.asarray(0, jnp.int32))
    m = n_microbatches or pp
    n_layer = jax.tree.leaves(xs)[0].shape[0]
    if n_layer % pp:
        raise ValueError(f"n_layer {n_layer} not divisible by pp={pp}")

    def shard_fn(x_local, xs_local, consts_):
        b = x_local.shape[0]
        if b % m:
            raise ValueError(
                f"local batch {b} not divisible by {m} microbatches "
                f"(global batch / (dp*fsdp) must divide pp_microbatches)"
            )
        stage = jax.lax.axis_index("pp")
        mbs = x_local.reshape(m, b // m, *x_local.shape[1:])
        state = jnp.zeros_like(mbs[0])
        outs = jnp.zeros_like(mbs)
        shift = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            state, outs, aux_tot = carry
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            state = jnp.where(stage == 0, inp, state)
            # the microbatch this stage holds at tick t entered at t - stage
            mb_idx = jnp.clip(t - stage, 0, m - 1).astype(jnp.int32)
            state, aux = apply_stack(state, xs_local, consts_, mb_idx)
            # warm-up/drain ticks process zero-padding, not data — mask
            # their aux out (outputs are filtered by the banking below)
            valid = (t >= stage) & (t - stage < m)
            aux_tot = aux_tot + jnp.where(valid, aux, 0.0)
            # bank stage pp-1's finished microbatch (index t - pp + 1)
            oidx = jnp.maximum(t - (pp - 1), 0)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
            bank = jnp.where((stage == pp - 1) & (t >= pp - 1), state, prev)
            outs = jax.lax.dynamic_update_index_in_dim(outs, bank, oidx, 0)
            state = jax.lax.ppermute(state, "pp", shift)
            return (state, outs, aux_tot), None

        (_, outs, aux_tot), _ = jax.lax.scan(
            tick,
            (state, outs, jnp.zeros((), jnp.float32)),
            jnp.arange(m + pp - 1),
        )
        # results live on the last stage; broadcast so every stage returns
        # the full activations (head/loss then run replicated over pp)
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp"
        )
        # aux: sum over stages (each holds different layers), mean over
        # microbatches and over the batch-ish/sequence shards — the same
        # estimator as the single-device full-batch mean
        aux = jax.lax.psum(aux_tot, "pp") / m
        aux = jax.lax.pmean(aux, BATCH_AXES + (("sp",) if seq_sharded else ()))
        return outs.reshape(x_local.shape), aux

    seq_ax = "sp" if seq_sharded else None
    x_spec = P(BATCH_AXES, seq_ax, *([None] * (x.ndim - 2)))
    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(x_spec, P("pp"), P()),
        out_specs=(x_spec, P()),
        check_vma=False,
    )
    return fn(x, xs, consts)
