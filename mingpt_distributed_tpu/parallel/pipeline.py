"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Beyond-parity strategy (SURVEY §2.2 marks PP "absent" in the reference —
blocks run as one nn.Sequential on one device, model.py:245-246). TPU-native
design: the stacked-layer axis of the block parameters (models/gpt.py stacks
all layers along a leading axis for ``lax.scan``) is *sharded* over ``pp``
— each stage holds n_layer/pp contiguous layers — and activations flow
stage-to-stage with ``lax.ppermute`` (point-to-point neighbour exchange, the
cheapest collective: rides a single ICI/DCN link per hop).

Schedule: classic GPipe. The local batch is split into M microbatches; the
loop runs M + pp - 1 ticks. At tick t, stage 0 ingests microbatch t, every
stage applies its layer stack to the microbatch it currently holds, stage
pp-1 banks its finished microbatch (t - pp + 1), and activations rotate one
hop. Bubble fraction (pp-1)/(M+pp-1) — raise ``cfg.pp_microbatches`` to
amortise. The whole schedule is one ``lax.scan`` inside one ``shard_map``,
so it is reverse-differentiable as-is: autodiff transposes ppermute into the
reverse hop and the backward pass runs the mirror-image pipeline.

Composition: pp composes with dp/fsdp batch sharding (specs below keep the
batch split over BATCH_AXES inside the region). Layer-granular tensor/
sequence parallelism inside a stage is not composed here — entering the
manual region gathers each stage's params over fsdp/tp (ZeRO-style
just-in-time gather; tp would need nested collectives the attention kernels
don't expect under manual mesh axes).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES


def pipeline_blocks(
    x: jax.Array,              # (B, T, D) activations (batch-sharded outside)
    xs: Any,                   # scanned-over pytree, leading global layer axis
    consts: Any,               # replicated extras (e.g. rope tables), pytree
    apply_stack: Callable[[jax.Array, Any, Any], jax.Array],
    mesh: Mesh,
    *,
    n_microbatches: int = 0,
) -> jax.Array:
    """Apply all layers to ``x`` across pipeline stages.

    ``apply_stack(x_mb, xs_local, consts, mb_idx)`` applies one stage's local
    layer stack (n_layer/pp layers) to one microbatch; ``mb_idx`` is the
    index of the microbatch being processed (fold it into any PRNG keys so
    stochastic ops like dropout decorrelate across microbatches).
    Semantically equivalent to scanning over the full layer axis on one
    device.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        return apply_stack(x, xs, consts, jnp.asarray(0, jnp.int32))
    m = n_microbatches or pp
    n_layer = jax.tree.leaves(xs)[0].shape[0]
    if n_layer % pp:
        raise ValueError(f"n_layer {n_layer} not divisible by pp={pp}")

    def shard_fn(x_local, xs_local, consts_):
        b = x_local.shape[0]
        if b % m:
            raise ValueError(
                f"local batch {b} not divisible by {m} microbatches "
                f"(global batch / (dp*fsdp) must divide pp_microbatches)"
            )
        stage = jax.lax.axis_index("pp")
        mbs = x_local.reshape(m, b // m, *x_local.shape[1:])
        state = jnp.zeros_like(mbs[0])
        outs = jnp.zeros_like(mbs)
        shift = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            state, outs = carry
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            state = jnp.where(stage == 0, inp, state)
            # the microbatch this stage holds at tick t entered at t - stage
            mb_idx = jnp.clip(t - stage, 0, m - 1).astype(jnp.int32)
            state = apply_stack(state, xs_local, consts_, mb_idx)
            # bank stage pp-1's finished microbatch (index t - pp + 1)
            oidx = jnp.maximum(t - (pp - 1), 0)
            prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
            bank = jnp.where((stage == pp - 1) & (t >= pp - 1), state, prev)
            outs = jax.lax.dynamic_update_index_in_dim(outs, bank, oidx, 0)
            state = jax.lax.ppermute(state, "pp", shift)
            return (state, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (state, outs), jnp.arange(m + pp - 1)
        )
        # results live on the last stage; broadcast so every stage returns
        # the full activations (head/loss then run replicated over pp)
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp"
        )
        return outs.reshape(x_local.shape)

    x_spec = P(BATCH_AXES, *([None] * (x.ndim - 1)))
    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(x_spec, P("pp"), P()),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(x, xs, consts)
