"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Beyond-parity strategy (SURVEY §2.2 marks PP "absent" in the reference —
blocks run as one nn.Sequential on one device, model.py:245-246). TPU-native
design: the stacked-layer axis of the block parameters (models/gpt.py stacks
all layers along a leading axis for ``lax.scan``) is *sharded* over ``pp``
— each stage holds n_layer/pp contiguous layers — and activations flow
stage-to-stage with ``lax.ppermute`` (point-to-point neighbour exchange, the
cheapest collective: rides a single ICI/DCN link per hop).

Schedule: classic GPipe. The local batch is split into M microbatches; the
loop runs M + pp - 1 ticks. At tick t, stage 0 ingests microbatch t, every
stage applies its layer stack to the microbatch it currently holds, stage
pp-1 banks its finished microbatch (t - pp + 1), and activations rotate one
hop. Bubble fraction (pp-1)/(M+pp-1) — raise ``cfg.pp_microbatches`` to
amortise. The whole schedule is one ``lax.scan`` inside one ``shard_map``,
so it is reverse-differentiable as-is: autodiff transposes ppermute into the
reverse hop and the backward pass runs the mirror-image pipeline.

Two schedules (``schedule=`` / cfg.pp_schedule):

* **"gpipe"** (default): the whole schedule is one plain differentiable
  scan — autodiff transposes ppermute into the reverse hop and derives the
  backward pipeline; combined with ``cfg.remat`` the stored state per tick
  is small, but the scan's saved carries still grow with the microbatch
  count M.
* **"1f1b"**: identical forward; the backward is a hand-written custom-vjp
  that re-runs the forward pipeline and interleaves each stage's transposed
  (backward) application with the recompute in classic 1F1B order — stage s
  transposes microbatch m exactly 2(pp-1-s) ticks after re-stashing its
  input, so the live stage-input stash is a ring buffer of 2(pp-1)+1
  microbatches: O(pp), independent of M. Compute cost is one extra forward
  vs GPipe+remat — 3 forwards + 1 backward per stage-microbatch (primal,
  the stash-rebuilding recompute, and the vjp's own linearization forward;
  the two bwd-tick forwards run on different microbatches so they cannot
  fuse). Choose it when M is large enough that GPipe's O(M) per-tick
  stashes dominate HBM and the ~25% step-FLOP tax is worth the headroom.
  Same bubble fraction either way.

  Measured (XLA memory_analysis/cost_analysis on the compiled pp=2, M=8
  tiny-GPT train step — test_pipeline.py::test_pp_schedule_cost_model_is_
  measured keeps the ordering pinned): gpipe no-remat 14.2 MB temp /
  49 GFLOP; gpipe+remat 1.7 MB / 54 GFLOP (+10%); 1f1b 3.1 MB / 63 GFLOP
  (+29%). So gpipe+remat is the default memory-saver; 1f1b's niche is
  avoiding remat's recompute *latency* inside each tick (its re-forward
  overlaps the pipeline) or models where jax.checkpoint granularity is
  too coarse.

Composition:
- pp x dp/fsdp: batch stays sharded over BATCH_AXES inside the region.
- pp x sp (``seq_sharded=True``): activations stay sequence-sharded inside
  the region too; the caller's ``apply_stack`` runs sequence-parallel
  attention (ring / Ulysses per-shard bodies over the ``sp`` axis — legal
  here because the pipeline's shard_map already manualises every mesh axis).
- pp x MoE: ``apply_stack`` returns a per-stage aux (load-balancing) loss;
  garbage warm-up/drain ticks are masked out, stages sum over ``pp`` and the
  batch-ish axes average, reproducing the single-device aux semantics.
  (Expert weights are gathered at stage entry like the rest of the stage's
  params — ZeRO-style JIT gather — so combine pp with ep=1.)
- pp x tp/fsdp (``xs_specs``): the caller may pass per-leaf PartitionSpecs
  for ``xs`` so stage parameters STAY tp/fsdp-sharded inside the manual
  region instead of being gathered at entry; ``apply_stack`` then owns the
  megatron math (models/gpt.py: per-shard heads/ffn columns, one psum over
  ``tp`` per residual branch, per-layer all_gather over ``fsdp``).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES
from mingpt_distributed_tpu.utils import compat


def _split_diff(tree):
    """Flatten a pytree and mark which leaves are differentiable (inexact
    dtype). PRNG-key and integer leaves (e.g. per-layer dropout keys riding
    the scanned xs) get float0 cotangents from the custom vjp."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    mask = [jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact) for l in flat]
    return flat, treedef, mask


def _rebuild(flat, treedef, mask, diff_vals):
    it = iter(diff_vals)
    return jax.tree_util.tree_unflatten(
        treedef, [next(it) if k else orig for orig, k in zip(flat, mask)]
    )


def _float0_cotangents(flat, treedef, mask, diff_cts):
    from jax import dtypes as jdtypes

    it = iter(diff_cts)
    out = [
        next(it) if k else np.zeros(np.shape(orig), jdtypes.float0)
        for orig, k in zip(flat, mask)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def _make_1f1b(tick_scan, apply_stack, pp: int, m: int):
    """Wrap the GPipe forward in a custom vjp whose backward runs the 1F1B
    interleave: one combined scan where every tick does (a) one forward
    recompute tick, stashing the stage's input in a ring buffer, and (b) one
    transposed (backward) application 2(pp-1-stage) ticks behind, consuming
    the stash and rotating the cotangent one hop backwards. Stage pp-1 has
    lag 0 — its backward starts the very tick its forward recompute runs —
    which is what bounds the stash at 2(pp-1)+1 in-flight microbatches."""
    lag = pp - 1
    stash_n = 2 * lag + 1
    fwd_shift = [(i, (i + 1) % pp) for i in range(pp)]
    rev_shift = [(i, (i - 1) % pp) for i in range(pp)]

    @jax.custom_vjp
    def run(mbs, xs, consts):
        return tick_scan(mbs, xs, consts)

    def fwd_rule(mbs, xs, consts):
        return tick_scan(mbs, xs, consts), (mbs, xs, consts)

    def bwd_rule(res, cts):
        mbs, xs, consts = res
        g_outs, g_aux = cts
        act_dtype = mbs.dtype
        xs_flat, xs_tree, xs_mask = _split_diff(xs)
        c_flat, c_tree, c_mask = _split_diff(consts)
        diff_xs = tuple(l for l, k in zip(xs_flat, xs_mask) if k)
        diff_c = tuple(l for l, k in zip(c_flat, c_mask) if k)

        def tick(carry, t):
            fstate, bstate, stash, g_mbs, g_xs, g_c = carry
            stage = jax.lax.axis_index("pp")

            # -- forward recompute (GPipe order), stashing stage INPUTS ----
            inp = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            fstate = jnp.where(stage == 0, inp, fstate)
            mb_f = jnp.clip(t - stage, 0, m - 1).astype(jnp.int32)
            fvalid = (t >= stage) & (t - stage < m)
            slot_f = mb_f % stash_n
            old = jax.lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(fvalid, fstate, old), slot_f, 0
            )
            fstate, _ = apply_stack(fstate, xs, consts, mb_f)
            fstate = jax.lax.ppermute(fstate, "pp", fwd_shift)

            # -- backward: transpose stage apply for mb (t - 2*lag + stage) -
            mb_b = t - 2 * lag + stage
            bvalid = (mb_b >= 0) & (mb_b < m)
            mb_bc = jnp.clip(mb_b, 0, m - 1).astype(jnp.int32)
            x_in = jax.lax.dynamic_index_in_dim(
                stash, mb_bc % stash_n, 0, keepdims=False
            )
            g_in = jnp.where(
                stage == lag,
                jax.lax.dynamic_index_in_dim(g_outs, mb_bc, 0, keepdims=False)
                .astype(act_dtype),
                bstate,
            )
            g_y = jnp.where(bvalid, g_in, jnp.zeros_like(g_in))
            # g_aux arrives shaped (1,) (the region-internal aux shape);
            # apply_stack's own aux output is scalar, so its ct must be too
            g_a = jnp.where(bvalid, g_aux.reshape(()), 0.0).astype(jnp.float32)

            def apply_d(x, dxs, dc):
                return apply_stack(
                    x,
                    _rebuild(xs_flat, xs_tree, xs_mask, dxs),
                    _rebuild(c_flat, c_tree, c_mask, dc),
                    mb_bc,
                )

            _, vjp_fn = jax.vjp(apply_d, x_in, diff_xs, diff_c)
            gx, g_dxs, g_dc = vjp_fn((g_y, g_a))
            gx = gx.astype(act_dtype)
            g_xs = jax.tree.map(jnp.add, g_xs, tuple(g_dxs))
            g_c = jax.tree.map(jnp.add, g_c, tuple(g_dc))
            gm_old = jax.lax.dynamic_index_in_dim(g_mbs, mb_bc, 0, keepdims=False)
            g_mbs = jax.lax.dynamic_update_index_in_dim(
                g_mbs, jnp.where((stage == 0) & bvalid, gx, gm_old), mb_bc, 0
            )
            bstate = jax.lax.ppermute(gx, "pp", rev_shift)
            return (fstate, bstate, stash, g_mbs, g_xs, g_c), None

        init = (
            jnp.zeros_like(mbs[0]),                                  # fstate
            jnp.zeros_like(mbs[0]),                                  # bstate
            jnp.zeros((stash_n, *mbs.shape[1:]), act_dtype),         # stash
            jnp.zeros_like(mbs),                                     # g_mbs
            tuple(jnp.zeros_like(l) for l in diff_xs),
            tuple(jnp.zeros_like(l) for l in diff_c),
        )
        (_, _, _, g_mbs, g_dxs, g_dc), _ = jax.lax.scan(
            tick, init, jnp.arange(m + 2 * lag)
        )
        return (
            g_mbs,
            _float0_cotangents(xs_flat, xs_tree, xs_mask, g_dxs),
            _float0_cotangents(c_flat, c_tree, c_mask, g_dc),
        )

    run.defvjp(fwd_rule, bwd_rule)
    return run


def pipeline_blocks(
    x: jax.Array,              # (B, T, D) activations (batch-sharded outside)
    xs: Any,                   # scanned-over pytree, leading global layer axis
    consts: Any,               # replicated extras (e.g. rope tables), pytree
    apply_stack: Callable[[jax.Array, Any, Any, jax.Array], Tuple[jax.Array, jax.Array]],
    mesh: Mesh,
    *,
    n_microbatches: int = 0,
    seq_sharded: bool = False,
    xs_specs: Any = None,
    schedule: str = "gpipe",
) -> Tuple[jax.Array, jax.Array]:
    """Apply all layers to ``x`` across pipeline stages.

    ``apply_stack(x_mb, xs_local, consts, mb_idx) -> (y_mb, aux)`` applies
    one stage's local layer stack (n_layer/pp layers) to one microbatch and
    returns its scalar aux loss (0 for dense MLPs); ``mb_idx`` is the index
    of the microbatch being processed (fold it into any PRNG keys so
    stochastic ops like dropout decorrelate across microbatches).
    ``seq_sharded`` keeps the sequence dim sharded over ``sp`` inside the
    region (apply_stack must then run sequence-parallel attention).
    ``xs_specs`` (a PartitionSpec pytree matching ``xs``) keeps stage params
    sharded over further axes (tp/fsdp) inside the region — apply_stack must
    then run the matching per-shard math; default gathers everything but the
    ``pp`` layer axis at entry.
    Returns (activations, aux) — semantically equivalent to scanning the
    full layer axis on one device.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        return apply_stack(x, xs, consts, jnp.asarray(0, jnp.int32))
    m = n_microbatches or pp
    n_layer = jax.tree.leaves(xs)[0].shape[0]
    if n_layer % pp:
        raise ValueError(f"n_layer {n_layer} not divisible by pp={pp}")

    def shard_fn(x_local, xs_local, consts_):
        b = x_local.shape[0]
        if b % m:
            raise ValueError(
                f"local batch {b} not divisible by {m} microbatches "
                f"(global batch / (dp*fsdp) must divide pp_microbatches)"
            )
        mbs = x_local.reshape(m, b // m, *x_local.shape[1:])
        shift = [(i, (i + 1) % pp) for i in range(pp)]

        def tick_scan(mbs_, xs_, consts_in):
            """GPipe forward ticks -> (outs, aux_tot); outs are banked on
            the last stage only (zeros elsewhere; broadcast happens below)."""

            def tick(carry, t):
                state, outs, aux_tot = carry
                stage = jax.lax.axis_index("pp")
                inp = jax.lax.dynamic_index_in_dim(
                    mbs_, jnp.clip(t, 0, m - 1), 0, keepdims=False
                )
                state = jnp.where(stage == 0, inp, state)
                # the microbatch this stage holds at tick t entered at t - stage
                mb_idx = jnp.clip(t - stage, 0, m - 1).astype(jnp.int32)
                state, aux = apply_stack(state, xs_, consts_in, mb_idx)
                # warm-up/drain ticks process zero-padding, not data — mask
                # their aux out (outputs are filtered by the banking below)
                valid = (t >= stage) & (t - stage < m)
                aux_tot = aux_tot + jnp.where(valid, aux, 0.0)
                # bank stage pp-1's finished microbatch (index t - pp + 1)
                oidx = jnp.maximum(t - (pp - 1), 0)
                prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
                bank = jnp.where((stage == pp - 1) & (t >= pp - 1), state, prev)
                outs = jax.lax.dynamic_update_index_in_dim(outs, bank, oidx, 0)
                state = jax.lax.ppermute(state, "pp", shift)
                return (state, outs, aux_tot), None

            # the aux accumulator rides as shape (1,), NOT a scalar: jaxlib
            # 0.4.x's shard_map partial-eval names every linearization
            # residual {0: all_axes}, which is rank-invalid for scalars and
            # makes jit(grad(...)) of the region raise _SpecError — keeping
            # every differentiable intermediate rank >= 1 sidesteps it
            # (scalarised again at the region boundary below).
            (_, outs, aux_tot), _ = jax.lax.scan(
                tick,
                (jnp.zeros_like(mbs_[0]), jnp.zeros_like(mbs_),
                 jnp.zeros((1,), jnp.float32)),
                jnp.arange(m + pp - 1),
            )
            return outs, aux_tot

        if schedule == "1f1b":
            outs, aux_tot = _make_1f1b(tick_scan, apply_stack, pp, m)(
                mbs, xs_local, consts_
            )
        else:
            outs, aux_tot = tick_scan(mbs, xs_local, consts_)
        stage = jax.lax.axis_index("pp")
        # results live on the last stage; broadcast so every stage returns
        # the full activations (head/loss then run replicated over pp).
        # The mask is materialised at rank outs.ndim rather than passed as
        # a scalar `where` condition: jaxlib 0.4.x's shard_map partial
        # eval names every residual {0: all_axes}, which is rank-invalid
        # for a scalar residual and makes jit(grad(...)) of this region
        # die with _SpecError — a rank-1+ residual sidesteps the bug.
        mask = (stage == pp - 1).astype(outs.dtype).reshape((1,) * outs.ndim)
        outs = jax.lax.psum(outs * mask, "pp")
        # aux: sum over stages (each holds different layers), mean over
        # microbatches and over the batch-ish/sequence shards — the same
        # estimator as the single-device full-batch mean
        aux = jax.lax.psum(aux_tot, "pp") / m
        aux = jax.lax.pmean(aux, BATCH_AXES + (("sp",) if seq_sharded else ()))
        return outs.reshape(x_local.shape), aux.reshape(())

    seq_ax = "sp" if seq_sharded else None
    x_spec = P(BATCH_AXES, seq_ax, *([None] * (x.ndim - 2)))
    fn = compat.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(x_spec, xs_specs if xs_specs is not None else P("pp"), P()),
        out_specs=(x_spec, P()),
        check_vma=False,
    )
    return fn(x, xs, consts)
