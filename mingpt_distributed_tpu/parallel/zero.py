"""ZeRO-style cross-replica weight-update sharding over the ``dp`` axis.

The mesh already gives ZeRO-3-style *parameter* sharding on ``fsdp`` for
free (PARAM_RULES applies to the AdamW moments leaf-for-leaf), but the
pure ``dp`` axis replicates params AND optimizer moments on every
replica: grads are all-reduced and every dp replica redundantly computes
the identical full AdamW update. This module implements the
weight-update-sharding transformation of "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training" (arXiv 2004.13336):

  reduce-scatter grads over dp  ->  clip/Adam/decay/lr on the local
  1/dp shard only  ->  allgather the updated params.

Everything here is driven by a static per-leaf ``ZeroPlan`` built once
from the abstract parameter shapes:

* **dim mode** — the largest dimension whose size the dp extent (times
  any axes already sharding that dimension) divides gets ``dp`` appended
  to its PartitionSpec entry. The leaf keeps its shape; only the layout
  changes.
* **flat mode** — small/indivisible leaves (biases, norm scales) are
  flattened to 1-D, zero-padded to a multiple of dp, and sharded
  ``P("dp")``. Padding is update-invariant: pad grads are zero, so Adam
  moments and updates for pad slots stay zero, and ``from_view`` drops
  the pad before the params are gathered back.

The *update view* (``update_view``/``from_view``) is the layout the
optimizer runs in; optimizer state is initialised from the view, so the
moments are physically 1/dp per device (``mesh.state_shardings`` with a
``zero_plan``). Checkpoints always store moments in the CANONICAL layout
(original shapes, no pad — ``canonical_opt_state``/``localize_opt_state``),
which is what makes a checkpoint written at dp=4 restore cleanly at
dp=2 or dp=1: the view is a function of the *restoring* mesh, not the
saving one.

Inside the jitted step the plan only ever makes static (python-level)
decisions — per-leaf mode, pad amount, spec — so the compiled program
contains no traced branching; the collectives are placed by GSPMD from
``with_sharding_constraint`` alone. ``optax.clip_by_global_norm`` stays
globally correct on the sharded view because GSPMD inserts the psum for
the norm reduction, and the pad zeros contribute nothing to it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mingpt_distributed_tpu.parallel import mesh as mesh_lib
from mingpt_distributed_tpu.utils.pytree import leaf_name

# per-leaf plan modes
DIM = "dim"    # append "dp" to the spec of one (dp-divisible) dimension
FLAT = "flat"  # flatten + zero-pad to a multiple of dp, shard P("dp")
NOOP = "noop"  # dp extent 1: the view is the identity


@dataclass(frozen=True)
class LeafPlan:
    """Static update-view layout for one parameter leaf."""

    name: str
    mode: str
    shape: Tuple[int, ...]       # canonical (model) shape
    view_shape: Tuple[int, ...]  # shape inside the update view
    spec: P                      # partition spec of the update view
    dim: int = -1                # sharded dimension (dim mode)
    pad: int = 0                 # zero slots appended (flat mode)


@dataclass(frozen=True)
class ZeroPlan:
    """Whole-tree plan: a pytree of LeafPlan mirroring the params, plus a
    name index for the (name-keyed) optimizer-moment trees."""

    mesh: Mesh
    dp: int
    leaves: Any                       # pytree of LeafPlan
    by_name: Dict[str, LeafPlan]


def _padded_spec(spec: P, ndim: int) -> list:
    """Spec entries as a list, one per dimension (P may be shorter)."""
    entries = list(spec)
    return entries + [None] * (ndim - len(entries))


def make_plan(mesh: Mesh, params_shape: Any) -> ZeroPlan:
    """Build the static per-leaf plan from abstract parameter shapes.

    The base spec is the PARAM_RULES spec after ``shard_by_rule``'s
    divisibility downgrade, so ``dp`` composes with whatever sharding the
    leaf actually gets (fsdp/tp/pp), never with what the rule wished for.
    """
    dp = int(mesh.shape["dp"])
    by_name: Dict[str, LeafPlan] = {}

    def plan_leaf(path, leaf) -> LeafPlan:
        name = leaf_name(path)
        shape = tuple(leaf.shape)
        base = mesh_lib.shard_by_rule(
            mesh, shape, mesh_lib._spec_for(path, leaf), name=name
        ).spec
        entries = _padded_spec(base, len(shape))
        if dp <= 1:
            lp = LeafPlan(name, NOOP, shape, shape, P(*entries))
            by_name[name] = lp
            return lp
        best, best_size = -1, 0
        for i, size in enumerate(shape):
            axes = entries[i]
            ax_tuple = (
                () if axes is None
                else (axes if isinstance(axes, tuple) else (axes,))
            )
            n = math.prod(mesh.shape[a] for a in ax_tuple)
            if size % (n * dp) == 0 and size > best_size:
                best, best_size = i, size
        if best >= 0:
            axes = entries[best]
            ax_tuple = (
                () if axes is None
                else (axes if isinstance(axes, tuple) else (axes,))
            )
            entries[best] = ax_tuple + ("dp",) if ax_tuple else "dp"
            lp = LeafPlan(name, DIM, shape, shape, P(*entries), dim=best)
        else:
            total = math.prod(shape) if shape else 1
            pad = (-total) % dp
            lp = LeafPlan(
                name, FLAT, shape, (total + pad,), P("dp"), pad=pad
            )
        by_name[name] = lp
        return lp

    leaves = jax.tree_util.tree_map_with_path(plan_leaf, params_shape)
    return ZeroPlan(mesh=mesh, dp=dp, leaves=leaves, by_name=by_name)


def _is_plan(x) -> bool:
    return isinstance(x, LeafPlan)


def update_view(tree: Any, plan: ZeroPlan) -> Any:
    """Canonical layout -> update view (jit-safe; shapes only, no layout —
    sharding comes from ``constrain``/``view_shardings``)."""

    def to_view(lp: LeafPlan, leaf):
        if lp.mode != FLAT:
            return leaf
        flat = jnp.reshape(leaf, (-1,))
        if lp.pad:
            flat = jnp.pad(flat, (0, lp.pad))
        return flat

    return jax.tree.map(to_view, plan.leaves, tree, is_leaf=_is_plan)


def from_view(tree: Any, plan: ZeroPlan) -> Any:
    """Update view -> canonical layout (drops flat-mode padding)."""

    def back(lp: LeafPlan, leaf):
        if lp.mode != FLAT:
            return leaf
        flat = leaf[: math.prod(lp.shape) if lp.shape else 1]
        return jnp.reshape(flat, lp.shape)

    return jax.tree.map(back, plan.leaves, tree, is_leaf=_is_plan)


def view_shardings(plan: ZeroPlan) -> Any:
    """NamedSharding pytree for the update view (mirrors the params)."""
    return jax.tree.map(
        lambda lp: NamedSharding(plan.mesh, lp.spec),
        plan.leaves, is_leaf=_is_plan,
    )


def constrain(tree: Any, plan: ZeroPlan) -> Any:
    """Pin the update view's layout inside jit. On the grads view this is
    what GSPMD lowers to a reduce-scatter over dp (all-reduce + slice
    fused); on the params view it is a local slice of the replicated
    copy (no communication)."""
    return jax.lax.with_sharding_constraint(tree, view_shardings(plan))


# ---------------------------------------------------------------------------
# Canonical <-> view optimizer-state layout (host-side, for checkpoints)
# ---------------------------------------------------------------------------

def _named_flat_leaf(plan: ZeroPlan, path, leaf, *, in_view: bool):
    """The FLAT LeafPlan for this opt-state leaf, or None.

    Moments (mu/nu) mirror the params pytree with the same leaf names;
    scalars (Adam's count) and anything else match no plan entry. The
    leaf must be in the transform's SOURCE layout (``in_view`` = view
    shape, else canonical), so a leaf already in the target layout
    passes through untouched (idempotent)."""
    lp = plan.by_name.get(leaf_name(path))
    if lp is None or lp.mode != FLAT:
        return None
    have = tuple(np.shape(leaf))
    source = lp.view_shape if in_view else lp.shape
    return lp if have == source else None


def canonical_opt_state(opt_state: Any, plan: ZeroPlan) -> Any:
    """View layout -> canonical layout (numpy; gathers nothing itself —
    call on host/full arrays). Checkpoints always store this layout, so
    snapshots are identical whether ``zero_dp`` was on or off and restore
    reshards to any dp extent."""

    def back(path, leaf):
        lp = _named_flat_leaf(plan, path, leaf, in_view=True)
        if lp is None:
            return leaf
        flat = np.asarray(leaf).reshape(-1)
        return flat[: math.prod(lp.shape) if lp.shape else 1].reshape(lp.shape)

    return jax.tree_util.tree_map_with_path(back, opt_state)


def localize_opt_state(opt_state: Any, plan: ZeroPlan) -> Any:
    """Canonical layout -> this plan's view layout (numpy, host-side):
    the restore-time half of reshard-on-restore."""

    def to_view(path, leaf):
        lp = _named_flat_leaf(plan, path, leaf, in_view=False)
        if lp is None:
            return leaf
        flat = np.asarray(leaf).reshape(-1)
        if lp.pad:
            flat = np.pad(flat, (0, lp.pad))
        return flat

    return jax.tree_util.tree_map_with_path(to_view, opt_state)


def canonical_opt_shape(opt_state_shape: Any, plan: ZeroPlan) -> Any:
    """Abstract (eval_shape) view-layout opt state -> canonical-layout
    ShapeDtypeStructs: the checkpoint skeleton ``load_snapshot`` pours
    into before ``localize_opt_state`` re-views it."""

    def back(path, leaf):
        # abstract leaves are in VIEW layout here; map view -> canonical
        lp = plan.by_name.get(leaf_name(path))
        if (
            lp is not None and lp.mode == FLAT
            and tuple(leaf.shape) == lp.view_shape
        ):
            return jax.ShapeDtypeStruct(lp.shape, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(back, opt_state_shape)


# ---------------------------------------------------------------------------
# Measurement helper (selftest / bench / dryrun)
# ---------------------------------------------------------------------------

def opt_moment_bytes(params_shape: Any, plan: "Optional[ZeroPlan]" = None,
                     ) -> int:
    """Analytic per-device bytes of the Adam moments (mu + nu) from
    shapes/dtypes alone — the zero_dp-aware HBM-ledger entry
    (telemetry/attribution.py). With a plan, each leaf's moments live in
    the update view sharded 1/dp over the dp axis (flat-mode pad slots
    included: they are real allocated zeros); without one, moments are
    replicated at full canonical size. dp-axis accounting only — any
    fsdp/tp sharding of the base spec is a property of the mesh the
    caller already divides by."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params_shape)[0]:
        itemsize = np.dtype(leaf.dtype).itemsize
        if plan is None or plan.dp <= 1:
            elems = math.prod(leaf.shape) if leaf.shape else 1
        else:
            lp = plan.by_name.get(leaf_name(path))
            if lp is None or lp.mode == NOOP:
                elems = math.prod(leaf.shape) if leaf.shape else 1
            else:
                view = math.prod(lp.view_shape) if lp.view_shape else 1
                elems = view // plan.dp
        total += 2 * elems * itemsize  # mu + nu
    return total


def per_device_bytes(tree: Any) -> int:
    """Bytes of ``tree`` held on the busiest addressable device — the
    per-chip memory cost the sharding actually achieves (a replicated
    leaf counts fully on every device; a 1/dp shard counts once)."""
    per: Dict[int, int] = {}
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        for s in shards:
            per[s.device.id] = per.get(s.device.id, 0) + s.data.nbytes
    return max(per.values()) if per else 0
