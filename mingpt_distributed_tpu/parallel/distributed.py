"""Multi-host runtime lifecycle — the ``init_process_group`` analogue.

The reference boots its distributed runtime with
``init_process_group(backend="nccl")`` / ``destroy_process_group``
(/root/reference/mingpt/train.py:34,58), fed by env vars torchrun sets
(RANK / WORLD_SIZE / MASTER_ADDR — slurm_run.sh:17-23). TPU-natively the
same contract is ``jax.distributed.initialize()``: the launcher (launch/)
starts one identical process per TPU host; the coordinator address is the
rendezvous endpoint; there is no backend string because XLA owns the
transport (ICI within a slice, DCN across slices — SURVEY §2.3).

On single-host (or under test) this is a no-op, so the same train.py runs
unchanged from a laptop CPU to a pod slice — the debuggability the reference
lacked by hard-coding NCCL (SURVEY §5.8).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job if one is configured; otherwise no-op.

    Resolution order: explicit args > env (COORDINATOR_ADDRESS / NUM_PROCESSES
    / PROCESS_ID — set by launch/tpu_pod_run.sh) > TPU metadata autodetection
    (jax.distributed.initialize() with no args on Cloud TPU). Single-process
    when nothing is configured.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("PROCESS_ID")

    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    elif os.environ.get("TPU_WORKER_HOSTNAMES") and _int_env("TPU_WORKER_ID") is not None:
        # Cloud TPU pod: jax autodetects topology from the metadata server.
        jax.distributed.initialize()
        _initialized = True
    # else: single-process run; nothing to do.


def shutdown() -> None:
    """destroy_process_group analogue (reference train.py:58)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None and v != "" else None
