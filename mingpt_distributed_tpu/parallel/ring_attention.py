"""Ring attention: sequence-parallel causal attention over the ``sp`` axis.

First-class long-context support (SURVEY §5.7 — the reference's strategy is
"crop to block_size"; this framework shards the *sequence* instead). Each
device on the ``sp`` mesh axis holds a contiguous sequence chunk of Q/K/V;
K/V chunks rotate around the ring with ``lax.ppermute`` while every device
accumulates its queries' attention with an online (streaming) softmax — the
same math as the flash kernel (ops/flash_attention.py), distributed: no
device ever materialises the full sequence, so max context scales linearly
with the ring size.

Causality around the ring: chunks are visited starting with the device's own
(step 0 = self-attention on the diagonal chunk, which guarantees every query
row sees at least one valid key before any fully-masked future chunk is
folded in — with the finite NEG_INF masking this keeps the accumulators
NaN-free). Fully-masked chunks then contribute exactly zero.

Chunk placement is **zigzag** on the flash path (half-chunk pair (i, 2n-1-i)
per device, redistributed internally): every hop then carries equal,
fully-live causal work — total kernel work per device is the exact causal
triangle share T^2/(2n) instead of the contiguous ring's ~T^2/n, and no hop
waits on a more-loaded neighbour. See ``_ring_shard_flash_zigzag``.

The rotation is a lax.scan (static ring length) so the whole thing is
reverse-differentiable — gradients flow through ppermute's transpose.
Implemented as a shard_map "manual" region usable inside the jitted,
GSPMD-partitioned train step.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.parallel import mesh as mesh_lib
from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES
from mingpt_distributed_tpu.utils import compat

NEG_INF = -1e30


def _ring_shard(q, k, v, *, axis_name: str, scale: float,
                window: Optional[int] = None,
                softcap: Optional[float] = None,
                pdrop: float = 0.0,
                key: Optional[jax.Array] = None):
    """Per-shard ring attention. q/k/v: (b, c, h, hd) local chunks.

    Dispatch: with a sliding window the banded ring runs — a contiguous
    ring that statically executes ONLY the hops whose chunk offset can
    intersect the band (see ``_ring_shard_flash_banded``); zigzag's
    load-balancing rationale is moot under a band, where per-query work is
    already uniform. Full-causal: when the local half-chunk is tileable,
    the zigzag flash ring runs — every hop carries equal, fully-useful
    causal work (see ``_ring_shard_flash_zigzag``). When only the full
    chunk is tileable, the contiguous flash ring runs (correct but ~2x the
    kernel work: future chunks are computed then folded with zero weight).
    Otherwise the fp32 einsum fold below is the oracle. ``softcap``
    composes with every path (the kernels apply it before masking).

    ``pdrop``/``key`` enable attention dropout (VERDICT r3 weak #4: the
    reference-default config has attn_pdrop=0.1, which previously knocked
    every sp path back to dense attention). The Pallas kernels carry no
    in-kernel RNG, so dropout rides the fp32 einsum ring: per-hop scores
    are (b, h, c, c) — the same memory class as the reference's dense
    attention, but still sequence-sharded and still streamed hop-by-hop.
    The mask for the (q-chunk, k-chunk) pair (i, j) is drawn from
    ``fold_in(key, i*n + j)``, so it is a pure function of the GLOBAL pair
    id — independent of ring placement, reproducible by a dense oracle.
    """
    from mingpt_distributed_tpu.ops import flash_attention as fa

    c = q.shape[1]
    n = jax.lax.psum(1, axis_name)
    if pdrop > 0.0 and key is not None:
        return _ring_shard_einsum(q, k, v, axis_name=axis_name, scale=scale,
                                  window=window, softcap=softcap,
                                  pdrop=pdrop, key=key)
    if window is not None:
        block = fa.supported_block(c)
        if n > 1 and block is not None:
            return _ring_shard_flash_banded(
                q, k, v, axis_name=axis_name, scale=scale, block=block,
                window=window, softcap=softcap,
            )
        return _ring_shard_einsum(q, k, v, axis_name=axis_name, scale=scale,
                                  window=window, softcap=softcap)
    if n > 1 and c % 2 == 0:
        half_block = fa.supported_block(c // 2)
        if half_block is not None:
            return _ring_shard_flash_zigzag(
                q, k, v, axis_name=axis_name, scale=scale, block=half_block,
                softcap=softcap,
            )
    block = fa.supported_block(c)
    if block is not None:
        return _ring_shard_flash(
            q, k, v, axis_name=axis_name, scale=scale, block=block,
            softcap=softcap,
        )
    return _ring_shard_einsum(q, k, v, axis_name=axis_name, scale=scale,
                              softcap=softcap)


def _ring_shard_flash_banded(q, k, v, *, axis_name: str, scale: float,
                             block: int, window: int,
                             softcap: Optional[float] = None):
    """Banded (sliding-window) ring attention with static hop skipping.

    With a window of W tokens over chunks of c tokens, a strictly-past
    chunk t hops back sits at offset D = t*c; its NEAREST key is D-(c-1)
    behind the query, so the chunk intersects the band iff
    t*c <= W + c - 2. The hop loop therefore runs only

        t_live = min(n-1, (W + c - 2) // c)

    hops — K/V chunks beyond the band are never rotated, never fetched,
    never computed: ring compute AND communication scale with T*W instead
    of T^2/2 (VERDICT r3 next #5: the model family that motivates
    sliding-window attention gets the sp axis that motivates long
    context). Per hop:

      - fully in-band pair (D + c - 1 < W): unmasked non-causal kernel;
      - boundary pair: the offset-banded kernel (q_offset = D) — its
        block-skipping prunes out-of-band tiles inside the chunk too.

    Wrapped sources (src > idx: future chunks) fold with weight 0 exactly
    like the contiguous ring; rows whose whole band precedes the received
    chunk emit lse ~= NEG_INF from the kernel and merge to zero weight
    (see flash_with_lse's dead-row contract).
    """
    from mingpt_distributed_tpu.ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, h, hd = q.shape

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, c, hd)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    # step 0 — own (diagonal) chunk: square banded-causal kernel; every
    # live row sees its diagonal key, so the running state starts NaN-free
    o0, lse0 = fa.flash_with_lse(qb, kb, vb, scale, block, True,
                                 window, softcap, 0)
    m, l, acc = lse0, jnp.ones_like(lse0), o0.astype(jnp.float32)

    t_live = min(n - 1, (window + c - 2) // c)
    shift = [(j, (j + 1) % n) for j in range(n)]
    kc, vc = kb, vb
    # python loop, not lax.scan: q_offset is a static kernel parameter that
    # differs per hop, and t_live is small (~window/c + 1) by construction
    for t in range(1, t_live + 1):
        kc = jax.lax.ppermute(kc, axis_name, shift)
        vc = jax.lax.ppermute(vc, axis_name, shift)
        d = t * c
        if d + c - 1 < window:
            # whole chunk pair inside the band: no masking needed at all
            oi, lsei = fa.flash_with_lse(qb, kc, vc, scale, block, False,
                                         None, softcap, 0)
        else:
            oi, lsei = fa.flash_with_lse(qb, kc, vc, scale, block, True,
                                         window, softcap, d)
        src = (idx - t) % n
        lsei = jnp.where(src < idx, lsei, NEG_INF)  # wrap = future chunk
        m_new = jnp.maximum(m, lsei)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lsei - m_new)
        m, l = m_new, l * alpha + w
        acc = acc * alpha + w * oi.astype(jnp.float32)
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(b, h, c, hd).transpose(0, 2, 1, 3)


def _ring_shard_flash_zigzag(q, k, v, *, axis_name: str, scale: float,
                             block: int, softcap: Optional[float] = None):
    """Zigzag ring attention (VERDICT r2 weak #2 / next #3).

    The contiguous ring gives device i all of chunk i: under causal masking
    device 0's queries need 1 chunk of K/V work and device n-1's need n, so
    every hop's wall-clock is the worst device's, and ~(n-1)/2 of the
    non-causal kernel launches are fully-masked work folded with weight 0.

    Zigzag placement fixes both: split the sequence into 2n half-chunks and
    give device i the pair (i, 2n-1-i) — one early, one late. For any
    received source chunk pair j != i exactly TWO half-blocks are causally
    live and both are *fully* live (no masking at all):

      j < i:  q_early x k_early(j)   and  q_late x k_early(j)
      j > i:  q_late  x k_early(j)   and  q_late x k_late(j)

    so every hop on every device runs the same two unmasked half-blocks —
    perfectly balanced, and total kernel work per device is T^2/(2n): the
    exact causal triangle share, vs ~T^2/n for the contiguous ring.

    The public contract is unchanged (contiguous global layout in and out):
    the zigzag redistribution is two ppermutes of half the local bytes on
    entry and exit. Both branch shapes are unified by batch-stacking the
    two live half-blocks, so the hop body stays a single lax.scan.
    """
    from mingpt_distributed_tpu.ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, h, hd = q.shape
    bh = b * h
    half = c // 2

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, c, hd)

    def zig_owner(hc: int) -> int:
        """Global half-chunk id -> zigzag owner device."""
        return hc if hc < n else 2 * n - 1 - hc

    # contiguous: device i holds global half-chunks (2i, 2i+1)
    perm_even = [(i, zig_owner(2 * i)) for i in range(n)]
    perm_odd = [(i, zig_owner(2 * i + 1)) for i in range(n)]
    even_first = (idx % 2) == 0  # is this device's early chunk the even one?

    def to_zigzag(xb):
        """(bh, c, hd) contiguous -> (early, late) zigzag half-chunks."""
        lo = jax.lax.ppermute(xb[:, :half], axis_name, perm_even)
        hi = jax.lax.ppermute(xb[:, half:], axis_name, perm_odd)
        # device d's pair {d, 2n-1-d} has exactly one even member (their sum
        # is odd); it arrived via perm_even. Order as (early=d, late=2n-1-d).
        early = jnp.where(even_first, lo, hi)
        late = jnp.where(even_first, hi, lo)
        return early, late

    qe, ql = to_zigzag(to_bh(q))
    ke, kl = to_zigzag(to_bh(k))
    ve, vl = to_zigzag(to_bh(v))

    def fold(state, o, lse):
        m, l, acc = state
        m_new = jnp.maximum(m, lse)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse - m_new)
        return (m_new, l * alpha + w, acc * alpha + w * o.astype(jnp.float32))

    # step 0 — own pair: early x early and late x late are diagonal
    # (causal), late x early is strictly past (full). Every query row sees
    # >= 1 key, so both running states start finite and NaN-free.
    o_ee, lse_ee = fa.flash_with_lse(qe, ke, ve, scale, block, True,
                                     None, softcap, 0)
    o_ll, lse_ll = fa.flash_with_lse(ql, kl, vl, scale, block, True,
                                     None, softcap, 0)
    o_le, lse_le = fa.flash_with_lse(ql, ke, ve, scale, block, False,
                                     None, softcap, 0)
    early = (lse_ee, jnp.ones_like(lse_ee), o_ee.astype(jnp.float32))
    late = fold((lse_ll, jnp.ones_like(lse_ll), o_ll.astype(jnp.float32)),
                o_le, lse_le)

    def body(carry, t):
        early, late, kec, klc, vec, vlc = carry
        # rotate both half-chunks one hop around the ring (ICI neighbours)
        shift = [(j, (j + 1) % n) for j in range(n)]
        kec, klc, vec, vlc = (
            jax.lax.ppermute(x, axis_name, shift) for x in (kec, klc, vec, vlc)
        )
        src = (idx - t) % n  # origin device of the pair we now hold
        past = src < idx
        # two live half-blocks, batch-stacked into ONE kernel call:
        #   past:  element a = q_early x k_early, element b = q_late x k_early
        #   else:  element a = q_late  x k_early, element b = q_late x k_late
        q2 = jnp.concatenate([jnp.where(past, qe, ql), ql], axis=0)
        k2 = jnp.concatenate([kec, jnp.where(past, kec, klc)], axis=0)
        v2 = jnp.concatenate([vec, jnp.where(past, vec, vlc)], axis=0)
        o2, lse2 = fa.flash_with_lse(q2, k2, v2, scale, block, False,
                                     None, softcap, 0)
        o_a, o_b = o2[:bh], o2[bh:]
        lse_a, lse_b = lse2[:bh], lse2[bh:]
        # element a belongs to early iff past; element b is always late
        early = fold(early, o_a, jnp.where(past, lse_a, NEG_INF))
        late = fold(late, o_b, lse_b)
        late = fold(late, o_a, jnp.where(past, NEG_INF, lse_a))
        return (early, late, kec, klc, vec, vlc), None

    (early, late, *_), _ = jax.lax.scan(
        body, (early, late, ke, kl, ve, vl), jnp.arange(1, n)
    )

    def finish(state):
        m, l, acc = state
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    out_e, out_l = finish(early), finish(late)
    # un-permute back to the contiguous layout (inverse exchanges)
    even_out = jnp.where(even_first, out_e, out_l)
    odd_out = jnp.where(even_first, out_l, out_e)
    inv_even = [(zig_owner(2 * i), i) for i in range(n)]
    inv_odd = [(zig_owner(2 * i + 1), i) for i in range(n)]
    lo = jax.lax.ppermute(even_out, axis_name, inv_even)
    hi = jax.lax.ppermute(odd_out, axis_name, inv_odd)
    out = jnp.concatenate([lo, hi], axis=1)
    return out.reshape(b, h, c, hd).transpose(0, 2, 1, 3)


def _ring_shard_flash(q, k, v, *, axis_name: str, scale: float, block: int,
                      softcap: Optional[float] = None):
    """Flash-kernel ring: the diagonal chunk runs the causal kernel; every
    rotated chunk runs the non-causal kernel and is folded via its
    log-sum-exp (future chunks fold with lse = -inf, i.e. exactly zero
    weight). Same math as the einsum fold, restated per chunk:
    out = sum_i exp(lse_i - LSE) * o_i with LSE = logsumexp_i(lse_i).
    """
    from mingpt_distributed_tpu.ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, h, hd = q.shape

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, c, hd)

    qb = to_bh(q)
    kb, vb = to_bh(k), to_bh(v)  # K/V ride the ring pre-transposed:
    # ppermute is layout-agnostic, so transposing once here (instead of per
    # hop inside the scan) removes 2*(n-1) layout copies per layer per step
    # step 0: own (diagonal) chunk, causal — every query row sees >= 1 key,
    # so the running state starts NaN-free
    o0, lse0 = fa.flash_with_lse(qb, kb, vb, scale, block, True,
                                 None, softcap, 0)
    m0 = lse0  # (bh, c, 1) fp32
    l0 = jnp.ones_like(lse0)  # exp(lse0 - m0)
    acc0 = o0.astype(jnp.float32)

    def body(carry, i):
        m, l, acc, kc, vc = carry
        # rotate K/V one hop around the ring (ICI neighbour exchange)
        shift = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, shift)
        vc = jax.lax.ppermute(vc, axis_name, shift)
        src = (idx - i) % n  # origin device of the chunk we now hold
        oi, lsei = fa.flash_with_lse(qb, kc, vc, scale, block, False,
                                     None, softcap, 0)
        # strictly-past chunks contribute; future chunks fold with zero
        # weight (finite NEG_INF keeps exp() well-defined)
        lsei = jnp.where(src < idx, lsei, NEG_INF)
        m_new = jnp.maximum(m, lsei)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lsei - m_new)
        l = l * alpha + w
        acc = acc * alpha + w * oi.astype(jnp.float32)
        return (m_new, l, acc, kc, vc), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, kb, vb), jnp.arange(1, n)
    )
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(b, h, c, hd).transpose(0, 2, 1, 3)


def _ring_shard_einsum(q, k, v, *, axis_name: str, scale: float,
                       window: Optional[int] = None,
                       softcap: Optional[float] = None,
                       pdrop: float = 0.0,
                       key: Optional[jax.Array] = None):
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, h, hd = q.shape
    qf = q.astype(jnp.float32) * scale

    q_pos = idx * c + jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
    k_local = jax.lax.broadcasted_iota(jnp.int32, (c, c), 1)

    def fold(m, l, acc, kc, vc, i):
        """Accumulate the currently-held K/V chunk into the online softmax."""
        src = (idx - i) % n  # origin device of the chunk we currently hold
        s = jnp.einsum(
            "bthd,bshd->bhts", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if softcap is not None:  # Gemma-2 soft-cap, before masking
            s = softcap * jnp.tanh(s / softcap)
        k_pos = src * c + k_local
        ok = q_pos >= k_pos
        if window is not None:
            ok = ok & (q_pos - k_pos < window)
        s = jnp.where(ok[None, None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # attention dropout = dropout(softmax(s)) @ v: the mask scales the
        # V-accumulator only; the normaliser l keeps the UN-dropped row sum
        # (softmax is computed first, then dropped). Mask keyed by the
        # global (q-chunk, k-chunk) pair id — placement-independent.
        pv = p
        if pdrop > 0.0 and key is not None:
            kij = jax.random.fold_in(key, idx * n + src)
            keep = jax.random.bernoulli(kij, 1.0 - pdrop, p.shape)
            pv = jnp.where(keep, p, 0.0) / (1.0 - pdrop)
        acc = acc * alpha + jnp.einsum(
            "bhts,bshd->bhtd", pv, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    def body(carry, i):
        m, l, acc, kc, vc = carry
        m, l, acc = fold(m, l, acc, kc, vc, i)
        # rotate K/V one hop around the ring (ICI neighbour exchange)
        shift = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, shift)
        vc = jax.lax.ppermute(vc, axis_name, shift)
        return (m, l, acc, kc, vc), None

    m0 = jnp.full((b, h, c, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, c, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, c, hd), jnp.float32)
    # scan the first n-1 hops; the last chunk is folded outside the scan so
    # its rotation (whose result nobody reads) never happens — one saved
    # K/V hop per layer per step
    (m, l, acc, kc, vc), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v), jnp.arange(n - 1)
    )
    m, l, acc = fold(m, l, acc, kc, vc, n - 1)
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhtd->bthd", out).astype(q.dtype)


def ring_causal_attention(
    q: jax.Array,  # (B, T, H, hd) global
    k: jax.Array,  # (B, T, KV, hd)
    v: jax.Array,
    mesh: Optional[Mesh],
    *,
    attn_pdrop: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    kv_offset: int | jax.Array = 0,
    window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """Sequence-parallel causal attention (einsum-oracle fallback when the
    ring doesn't apply: no mesh / sp==1 / dropout / decode shapes).

    ``window``/``logit_softcap`` compose with the ring (VERDICT r3 next
    #5): a sliding window turns the ring banded with static hop skipping
    (see _ring_shard_flash_banded), so the mistral-family presets can
    sequence-parallelize their long contexts.

    Attention dropout also composes (VERDICT r3 weak #4): the ring stays
    sequence-parallel under the reference-default ``attn_pdrop=0.1`` —
    the dropped path rides the einsum inner (see ``_ring_shard``) instead
    of silently degrading to a fully-gathered dense attention.
    """
    b, t, h, hd = q.shape
    drop = (not deterministic) and attn_pdrop > 0.0
    usable = (
        mesh is not None
        and mesh.shape.get("sp", 1) > 1
        and t == k.shape[1]
        and (not drop or dropout_key is not None)
        and isinstance(kv_offset, int)
        and kv_offset == 0
        and t % mesh.shape["sp"] == 0
    )
    if not usable:
        return attn_ops.causal_attention(
            q, k, v, attn_pdrop=attn_pdrop, dropout_key=dropout_key,
            deterministic=deterministic, kv_offset=kv_offset, window=window,
            logit_softcap=logit_softcap,
        )
    kv = k.shape[2]
    k = attn_ops.repeat_kv(k, h // kv)
    v = attn_ops.repeat_kv(v, h // kv)
    scale = 1.0 / math.sqrt(hd)
    # heads may be tensor-parallel; replicate over tp if indivisible
    head_ax = "tp" if h % mesh.shape.get("tp", 1) == 0 else None
    # head_dim stays unmentioned (GL011: trailing dims replicate)
    spec = P(BATCH_AXES, "sp", head_ax)
    shard = partial(_ring_shard, axis_name="sp", scale=scale,
                    window=None if window is None else int(window),
                    softcap=None if logit_softcap is None
                    else float(logit_softcap))
    if drop:
        # decorrelation policy (batch-shard fold + tp head-shard fold when
        # heads are genuinely tp-sharded) is single-sourced in
        # mesh.dropped_attention_shard_map; the shard body folds the global
        # (q-chunk, k-chunk) pair id on top
        fn = mesh_lib.dropped_attention_shard_map(
            shard, mesh, spec, attn_pdrop,
            # fold the head-shard coordinate only when tp genuinely splits
            # the heads (tp=1 would just add a constant fold_in(key, 0),
            # breaking the documented oracle-reproducible key derivation)
            head_axis=head_ax if mesh.shape.get("tp", 1) > 1 else None,
        )
        return fn(q, k, v, dropout_key)
    fn = compat.shard_map(
        shard,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
