"""Device mesh + sharding rules — the framework's distributed backbone.

Replaces the reference's distributed runtime (SURVEY §1-L1/§2.3): where the
reference wraps the model in DDP over NCCL (/root/reference/mingpt/trainer.py:71,
train.py:34) and shards data with DistributedSampler (trainer.py:80), here a
named ``jax.sharding.Mesh`` over all addressable devices carries every
parallelism axis, and XLA compiles the collectives (psum over ICI within a
slice, DCN across hosts) directly into the training step:

  pp    pipeline parallelism (GPipe stages over the stacked layer axis,
        parallel/pipeline.py)
  dp    pure data parallelism (the reference's only axis — grad all-reduce)
  fsdp  data parallelism + ZeRO-style parameter/optimizer sharding
        (BASELINE config #4: "pjit param sharding, DDP->GSPMD/FSDP analogue")
  ep    expert parallelism for MoE (ops/moe.py); also shards the batch
        outside expert layers, GShard-style
  tp    megatron-style tensor parallelism (column/row-split matmuls)
  sp    sequence/context parallelism for ring attention (long-context axis)

The model stays parallelism-unaware (SURVEY §1-L2's separation, preserved):
these rules attach NamedShardings to the *pytree* from outside; forward never
mentions an axis.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mingpt_distributed_tpu.config import MeshConfig
from mingpt_distributed_tpu.utils.pytree import leaf_name
from mingpt_distributed_tpu.utils import compat

# pp outermost: pipeline stages exchange activations point-to-point once per
# microbatch tick — the least bandwidth-hungry axis, so it can cross DCN;
# tp/sp innermost ride ICI.
AXES = ("pp", "dp", "fsdp", "ep", "tp", "sp")
# Batch is split over every data-ish axis; dp, fsdp and ep all shard the
# batch (ep doubles as a data axis outside expert layers, GShard-style),
# sp shards the sequence (ring attention), tp replicates the batch.
BATCH_AXES = ("dp", "fsdp", "ep")


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> tuple[int, ...]:
    """Resolve -1 entries ("absorb remaining devices") and validate."""
    dims = [cfg.pp, cfg.dp, cfg.fsdp, cfg.ep, cfg.tp, cfg.sp]
    if dims.count(-1) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {dims}")
    known = math.prod(d for d in dims if d != -1)
    if -1 in dims:
        if n_devices % known != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {known}"
            )
        dims[dims.index(-1)] = n_devices // known
    if math.prod(dims) != n_devices:
        raise ValueError(
            f"mesh {dict(zip(AXES, dims))} needs {math.prod(dims)} devices, "
            f"have {n_devices}"
        )
    return tuple(dims)


def make_mesh(
    cfg: Optional[MeshConfig] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the named mesh.

    Without an explicit device list, device placement is delegated to
    ``jax.experimental.mesh_utils.create_device_mesh``, which knows the
    physical TPU topology (ICI torus links) and lays the mesh out so the
    fastest-varying axes (tp, sp — tensor/sequence collectives) ride ICI
    while dp/fsdp cross slices/DCN (SURVEY §2.3's ICI/DCN mapping). A naive
    ``jax.devices()`` reshape instead assumes neighbouring ids are ICI
    neighbours, which real multi-host slices violate.

    Passing ``devices`` explicitly is the escape hatch for tests and for the
    driver's virtual-CPU dry run: those devices are used in the given order.
    """
    cfg = cfg or MeshConfig()
    if devices is not None:
        devs = list(devices)
        shape = resolve_mesh_shape(cfg, len(devs))
        arr = np.array(devs).reshape(shape)
        return Mesh(arr, AXES)
    shape = resolve_mesh_shape(cfg, len(jax.devices()))
    from jax.experimental import mesh_utils

    arr = mesh_utils.create_device_mesh(shape)
    return Mesh(arr, AXES)


def dropped_attention_shard_map(shard, mesh: Mesh, spec: P, pdrop: float,
                                head_axis: Optional[str] = None):
    """shard_map wrapper for sequence-parallel attention bodies under
    attention dropout (single-sourced decorrelation policy — used by both
    ring_attention and ulysses public wrappers).

    The dropout key rides in replicated (P()); each shard folds in

      - its batch-shard coordinate over ``BATCH_AXES`` — the dense GSPMD
        path draws masks per *global* row, so dp/fsdp/ep shards holding
        different rows must draw different masks;
      - its ``head_axis`` coordinate, ONLY when the q/k/v specs actually
        shard heads over that axis — tp shards then hold different global
        heads and must draw per-head-independent masks (mirroring the
        k_attn fold in models/gpt._block's manual-tp branch). When heads
        are *replicated* over tp (head_axis=None) every replica must draw
        the SAME mask or the replicas would diverge.

    The shard body then folds finer-grained ids (the ring's global
    (q-chunk, k-chunk) pair id; ulysses' head-group index) on top.
    """

    def dropped(q, k, v, key):
        key = jax.random.fold_in(key, jax.lax.axis_index(BATCH_AXES))
        if head_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(head_axis))
        return shard(q, k, v, pdrop=pdrop, key=key)

    return compat.shard_map(
        dropped, mesh=mesh, in_specs=(spec, spec, spec, P()),
        out_specs=spec, check_vma=False,
    )


def batch_spec() -> P:
    """(batch, seq) inputs: batch over dp+fsdp, seq over sp."""
    return P(BATCH_AXES, "sp")


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Parameter sharding rules
# ---------------------------------------------------------------------------

# name -> PartitionSpec over the *parameter pytree* produced by models/gpt.py.
# Block params carry a leading layer axis (scanned), never sharded.
# Convention (scaling-book megatron recipe):
#   column-parallel (d_model -> wide): input dim fsdp, output dim tp
#   row-parallel   (wide -> d_model): input dim tp,   output dim fsdp
# so a block's tp collectives are one all-gather + one reduce-scatter pair,
# and fsdp gathers params just-in-time per layer (ZeRO-3 analogue via GSPMD).
# Specs are authored in normalized form — no trailing Nones (GL011):
# unmentioned trailing dims replicate, and the runtime strips trailing
# Nones anyway, so the spelled form only breaks sharding-equality keys.
PARAM_RULES: dict[str, P] = {
    "wte": P("fsdp", "tp"),
    "wpe": P(),
    "head": P("tp", "fsdp"),
    "lnf_scale": P(),
    "lnf_bias": P(),
    # blocks (leading layer axis, sharded over pipeline stages; pp=1 = no-op)
    "wq": P("pp", "fsdp", "tp"),
    "wk": P("pp", "fsdp", "tp"),
    "wv": P("pp", "fsdp", "tp"),
    "wo": P("pp", "tp", "fsdp"),
    "w_fc": P("pp", "fsdp", "tp"),
    "w_gate": P("pp", "fsdp", "tp"),
    "w_up": P("pp", "fsdp", "tp"),
    "w_proj": P("pp", "tp", "fsdp"),
    "w_down": P("pp", "tp", "fsdp"),
    "bq": P("pp", "tp"),
    "bk": P("pp", "tp"),
    "bv": P("pp", "tp"),
    "bo": P("pp"),
    "b_fc": P("pp", "tp"),
    "b_proj": P("pp"),
    "ln1_scale": P("pp"),
    "ln1_bias": P("pp"),
    "ln2_scale": P("pp"),
    "ln2_bias": P("pp"),
    # MoE (ops/moe.py): expert axis over ep; expert matrices additionally
    # fsdp/tp-sharded like their dense counterparts
    "w_router": P("pp"),
    "w_e1": P("pp", "ep", "fsdp", "tp"),
    "w_e2": P("pp", "ep", "tp", "fsdp"),
    "w_eg": P("pp", "ep", "fsdp", "tp"),
}


def _spec_for(path, leaf) -> P:
    name = leaf_name(path)
    try:
        return PARAM_RULES[name]
    except KeyError:
        raise ValueError(
            f"no sharding rule for parameter {jax.tree_util.keystr(path)!r}"
        ) from None


def param_specs(params_shape: Any) -> Any:
    """PartitionSpec pytree for a (possibly abstract) parameter pytree."""
    return jax.tree_util.tree_map_with_path(_spec_for, params_shape)


# Leaf names whose rule has already been observed downgrading on this
# process — each (param, axes) surprise is logged/counted exactly once,
# not once per mesh rebuild or per moment tree that shares the name.
_DOWNGRADES_SEEN: set = set()


def _note_downgrade(name: str, axes, size: int, n: int) -> None:
    key = (name, axes)
    if key in _DOWNGRADES_SEEN:
        return
    _DOWNGRADES_SEEN.add(key)
    from mingpt_distributed_tpu import telemetry

    telemetry.get_registry().counter(
        "mingpt_train_sharding_downgrades_total",
        help="Parameter-sharding rules silently downgraded to replication "
             "because the mesh axis extent does not divide the dimension.",
        labels=("param",),
    ).labels(param=name).inc()
    telemetry.log_event(
        f"sharding downgrade: {name} dim of size {size} not divisible by "
        f"mesh extent {n} of axes {axes!r} — replicating that dimension",
        param=name,
    )


def shard_by_rule(
    mesh: Mesh, shape: Sequence[int], spec: P, name: Optional[str] = None
) -> NamedSharding:
    """NamedSharding for one array, downgrading (replicating) any spec axis
    whose mesh extent doesn't divide the dimension — tiny models on big
    meshes shard what they can instead of failing. When ``name`` is given,
    each downgrade is logged once and counted in
    ``mingpt_train_sharding_downgrades_total{param}`` so surprise
    replication shows up in scrapes instead of only in the memory bill."""
    fixed = []
    for size, axes in zip(shape, spec):
        if axes is None:
            fixed.append(None)
            continue
        ax_tuple = axes if isinstance(axes, tuple) else (axes,)
        n = math.prod(mesh.shape[a] for a in ax_tuple)
        if size % n == 0:
            fixed.append(axes)
        else:
            if name is not None:
                _note_downgrade(name, axes, size, n)
            fixed.append(None)
    return NamedSharding(mesh, P(*fixed))


def param_shardings(mesh: Mesh, params_shape: Any) -> Any:
    """NamedSharding pytree for model params (divisibility-validated)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: shard_by_rule(
            mesh, leaf.shape, _spec_for(path, leaf), name=leaf_name(path)
        ),
        params_shape,
    )


def state_shardings(mesh: Mesh, state_shape: Any, zero_plan=None) -> Any:
    """NamedShardings for a whole TrainState-like pytree.

    Optimizer moments (mu/nu) mirror the params pytree leaf-for-leaf with the
    same leaf names, so PARAM_RULES applies to them unchanged — ZeRO-style
    sharded optimizer state for free (BASELINE config #4). Scalars and
    unrecognised leaves replicate.

    With a ``zero_plan`` (parallel/zero.py), opt-state moment leaves get the
    plan's dp-sharded *update-view* spec instead, so Adam's mu/nu are
    physically 1/dp per device. Only leaves under the ``opt_state`` key
    whose shape matches the plan's view shape are re-routed — the params
    themselves keep their canonical sharding (they are gathered back after
    every update)."""

    def rule(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return NamedSharding(mesh, P())
        name = leaf_name(path)
        if (
            zero_plan is not None
            and path
            and getattr(path[0], "key", None) == "opt_state"
        ):
            lp = zero_plan.by_name.get(name)
            if lp is not None and tuple(leaf.shape) == tuple(lp.view_shape):
                return NamedSharding(mesh, lp.spec)
        if name in PARAM_RULES:
            return shard_by_rule(mesh, leaf.shape, PARAM_RULES[name], name=name)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, state_shape)
