"""Ulysses attention: all-to-all sequence parallelism over the ``sp`` axis.

The second long-context strategy (complementing ring attention,
parallel/ring_attention.py — SURVEY §2.2 lists both as absent from the
reference; this framework treats long context as first-class). Where the
ring streams K/V chunks around the ICI ring with an online softmax, Ulysses
(DeepSpeed-style) re-shards: an all-to-all converts the layout from
"sequence-sharded, all heads" to "head-sharded, full sequence", each device
runs ordinary *local* causal attention for its head group (reusing the
Pallas flash kernel — the two compose), and a second all-to-all restores
sequence sharding.

Trade-offs vs ring: two all-to-alls of the whole activation per layer
instead of n_ring K/V hops, no wasted upper-triangle compute, but requires
``n_head % sp == 0`` and holds the full sequence per device for the local
attention (memory bound by T·H/sp·hd, fine when flash attention keeps the
score matrix blockwise).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.ops import flash_attention as flash
from mingpt_distributed_tpu.parallel import mesh as mesh_lib
from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES
from mingpt_distributed_tpu.utils import compat


def _ulysses_shard(q, k, v, *, axis_name: str, window=None, softcap=None,
                   pdrop: float = 0.0, key=None):
    """Per-shard: (b, T/n, H, hd) -> attention output, via two all-to-alls.

    ``window``/``softcap`` compose for free: after the first all-to-all
    each device holds the FULL sequence for its head group, so the local
    banded/soft-capped kernel is exactly the dense semantics — no
    cross-chunk band bookkeeping as in the ring. Attention dropout
    (``pdrop``/``key``) likewise: the local call draws its mask from the
    key folded with the head-group index, so each group's heads get
    independent masks exactly as in the dense path (VERDICT r3 weak #4).
    """
    # seq-sharded/all-heads -> head-sharded/full-seq
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qh, kh, vh = a2a(q), a2a(k), a2a(v)  # (b, T, H/n, hd)
    # local attention over the full sequence for this head group; the flash
    # wrapper picks the Pallas kernel when shapes allow, einsum otherwise
    # (with dropout active it is the einsum oracle: no in-kernel RNG)
    drop_kw = {}
    if pdrop > 0.0 and key is not None:
        drop_kw = dict(
            attn_pdrop=pdrop,
            dropout_key=jax.random.fold_in(
                key, jax.lax.axis_index(axis_name)),
            deterministic=False,
        )
    out = flash.causal_attention(qh, kh, vh, window=window,
                                 logit_softcap=softcap, **drop_kw)
    # head-sharded/full-seq -> seq-sharded/all-heads
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_causal_attention(
    q: jax.Array,  # (B, T, H, hd) global
    k: jax.Array,  # (B, T, KV, hd)
    v: jax.Array,
    mesh: Optional[Mesh],
    *,
    attn_pdrop: float = 0.0,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    kv_offset: int | jax.Array = 0,
    window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """All-to-all sequence-parallel causal attention (oracle fallback when
    the strategy doesn't apply)."""
    b, t, h, hd = q.shape
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    drop = (not deterministic) and attn_pdrop > 0.0
    usable = (
        mesh is not None
        and sp > 1
        and t == k.shape[1]
        and (not drop or dropout_key is not None)
        and isinstance(kv_offset, int)
        and kv_offset == 0
        and t % sp == 0
        and h % sp == 0
    )
    if not usable:
        return attn_ops.causal_attention(
            q, k, v, attn_pdrop=attn_pdrop, dropout_key=dropout_key,
            deterministic=deterministic, kv_offset=kv_offset, window=window,
            logit_softcap=logit_softcap,
        )
    kv = k.shape[2]
    k = attn_ops.repeat_kv(k, h // kv)
    v = attn_ops.repeat_kv(v, h // kv)
    # heads/head_dim stay unmentioned (GL011: trailing dims replicate)
    spec = P(BATCH_AXES, "sp")
    shard = partial(_ulysses_shard, axis_name="sp",
                    window=None if window is None else int(window),
                    softcap=None if logit_softcap is None
                    else float(logit_softcap))
    if drop:
        # decorrelation policy single-sourced in mesh_lib (heads are
        # replicated over tp in this wrapper -> no head_axis fold; the
        # shard body folds its head-group index on top)
        fn = mesh_lib.dropped_attention_shard_map(
            shard, mesh, spec, attn_pdrop, head_axis=None,
        )
        return fn(q, k, v, dropout_key)
    fn = compat.shard_map(
        shard,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
