"""Rule battery. Importing this package registers every rule; the
modules are imported in ID order so ``--list-rules`` output is stable."""

from mingpt_distributed_tpu.analysis.rules import (  # noqa: F401
    donation,
    recompile,
    tracer_leak,
    clock,
    metric_names,
    print_discipline,
    sharding,
)
