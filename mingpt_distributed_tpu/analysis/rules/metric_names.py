"""GL008/GL009 — metric naming and cross-file registry coherence.

The motivating design (PR 4): the whole point of the unified
``MetricsRegistry`` is that every exporter reads one catalog with one
naming convention — ``mingpt_<subsystem>_<what>[_total|_seconds]``
(``docs/architecture.md`` "Telemetry"). A misnamed family quietly
splits the scrape page; a name registered as a counter in one file and
a gauge in another raises deep inside exposition at runtime; a typo'd
name literal in a selftest assertion matches nothing and the assert
tests air.

* **GL008 metric-name** — the literal first argument of a
  ``.counter(...)``/``.gauge(...)``/``.histogram(...)`` registration
  must match ``mingpt_<subsystem>_<what>`` (f-strings are checked by
  their literal prefix, which must cover ``mingpt_<subsystem>_``).
* **GL009 metric-conflict** (cross-file, emitted in ``finalize``) —
  the same family name registered with two different instrument types
  anywhere in the scanned set (registering the same name with the SAME
  type in two files is fine and idiomatic: the registry get-or-creates,
  e.g. ``mingpt_serving_rejected_total`` shared by scheduler and
  fleet); and any standalone ``mingpt_*`` string literal that matches
  no registered family — a typo'd scrape assertion or dashboard key.
  The unregistered-literal check only runs when the scan actually saw
  registrations, so linting a single script never false-positives.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import call_name

_REGISTER_METHODS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"^mingpt_[a-z][a-z0-9]*_[a-z0-9_]*[a-z0-9]$")
_PREFIX_RE = re.compile(r"^mingpt_[a-z][a-z0-9]*_")
#: a standalone literal that *looks like* one of our metric names
_LITERAL_RE = re.compile(r"^mingpt_[a-z0-9_]+$")


def _registration(node: ast.Call) -> Optional[Tuple[str, str, bool]]:
    """(name, instrument_type, is_fstring_prefix) when this call
    registers a metric family with a literal-ish name."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _REGISTER_METHODS):
        return None
    if not node.args:
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return (first.value, f.attr, False)
    if isinstance(first, ast.JoinedStr) and first.values \
            and isinstance(first.values[0], ast.Constant) \
            and isinstance(first.values[0].value, str):
        return (first.values[0].value, f.attr, True)
    return None


@register_rule
class MetricNameRule(Rule):
    id = "GL008"
    name = "metric-name"
    help = ("registered metric names must match "
            "mingpt_<subsystem>_<what> (docs/architecture.md Telemetry)")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            reg = _registration(n)
            if reg is None:
                continue
            name, itype, is_prefix = reg
            ok = (_PREFIX_RE.match(name) if is_prefix
                  else _NAME_RE.match(name))
            if not ok:
                shown = f"{name}{{…}}" if is_prefix else name
                findings.append(self.finding(
                    ctx, n,
                    f"metric {itype} name {shown!r} does not follow "
                    f"mingpt_<subsystem>_<what> — one naming scheme is "
                    f"what keeps the scrape page one catalog"))
        return findings


@register_rule
class MetricConflictRule(Rule):
    id = "GL009"
    name = "metric-conflict"
    help = ("one family name registered with two instrument types, or a "
            "mingpt_* literal that matches no registered family (typo'd "
            "scrape assertion)")

    def __init__(self) -> None:
        # name -> (instrument_type, path, line) of first sighting
        self._families: Dict[str, Tuple[str, str, int]] = {}
        self._fstring_prefixes: List[str] = []
        self._conflicts: List[Finding] = []
        # (finding, literal) for post-scan resolution
        self._literals: List[Tuple[Finding, str]] = []

    def check_file(self, ctx: FileContext) -> List[Finding]:
        registration_nodes = set()
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            reg = _registration(n)
            if reg is None:
                continue
            registration_nodes.add(id(n.args[0]))
            name, itype, is_prefix = reg
            if is_prefix:
                self._fstring_prefixes.append(name)
                continue
            prev = self._families.get(name)
            if prev is None:
                self._families[name] = (itype, ctx.relpath, n.lineno)
            elif prev[0] != itype:
                self._conflicts.append(self.finding(
                    ctx, n,
                    f"metric {name!r} registered as {itype} here but as "
                    f"{prev[0]} at {prev[1]}:{prev[2]} — exposition "
                    f"would raise a type conflict at runtime"))
        # standalone literals that look like metric names (skip f-string
        # fragments — they are prefixes, not full names — and the
        # registration args themselves)
        parent_join = {id(v) for n in ast.walk(ctx.tree)
                       if isinstance(n, ast.JoinedStr) for v in n.values}
        for n in ast.walk(ctx.tree):
            if not (isinstance(n, ast.Constant) and isinstance(n.value, str)):
                continue
            if id(n) in registration_nodes or id(n) in parent_join:
                continue
            lit = n.value.split("{", 1)[0]
            # the package itself matches the lexical pattern — module
            # paths like "mingpt_distributed_tpu.serving" are not metric
            # names
            if lit.startswith("mingpt_distributed_tpu"):
                continue
            if _LITERAL_RE.match(lit):
                self._literals.append((self.finding(
                    ctx, n,
                    f"metric name literal {lit!r} matches no registered "
                    f"family — typo, or the family was renamed without "
                    f"updating this consumer"), lit))
        return self._conflicts_drain()

    def _conflicts_drain(self) -> List[Finding]:
        out, self._conflicts = self._conflicts, []
        return out

    def finalize(self) -> List[Finding]:
        if not self._families and not self._fstring_prefixes:
            return []  # scan saw no registrations: nothing to check against
        out: List[Finding] = []
        for f, lit in self._literals:
            known = any(lit == fam or lit.startswith(fam + "_")
                        for fam in self._families)
            if not known:
                known = any(lit.startswith(p) for p in self._fstring_prefixes)
            if not known:
                out.append(f)
        return out
