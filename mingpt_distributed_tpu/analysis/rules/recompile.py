"""GL002/GL003/GL004/GL005 — recompile hazards.

The motivating incident (PR 4): silent recompiles cost us enough real
debugging time that we built a *runtime* ``RecompileWatchdog``
(``telemetry/watchdog.py``) that arms after warmup and counts trace
growth. The watchdog catches recompiles in production; these rules catch
the three coding patterns that cause them, at review time:

* **GL002 traced-coercion** — ``str()``/``int()``/``float()``/
  ``bool()`` or an f-string applied to a traced value inside jitted
  code. Under trace these either raise (``int`` on a tracer) or, worse,
  bake a concrete value into the program via a host sync and retrace on
  the next distinct value.
* **GL003 traced-branch** — Python ``if``/``while``/``assert``/ternary
  on a traced value. Same failure shape: ``TracerBoolConversionError``
  at best, a silent per-value specialisation at worst. Branch on static
  args (fine, that's what they're for) or use ``jnp.where``/
  ``jax.lax.cond``.
* **GL004 jit-in-loop** — ``jax.jit(...)`` constructed inside a
  ``for``/``while`` body. A fresh jit wrapper has a fresh trace cache,
  so per-step/per-request construction recompiles every iteration —
  the serving engine's whole design (two lifetime-compiled programs) is
  the counter-pattern. Compile-behaviour experiments under
  ``tools/exp_*`` do this on purpose and are exempt by config.
* **GL005 unhashable-static** — a list/dict/set literal passed at a
  ``static_argnums``/``static_argnames`` position of a module-local
  jitted callable. Static args are cache keys; unhashables raise at
  call time, and mutable-but-hashable wrappers silently key the cache
  on identity.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import (
    TracedTaint, call_name, collect_jitted, is_jax_jit, is_partial,
)

_COERCIONS = {"str", "int", "float", "bool", "format"}


def _walk_scope(root: ast.AST):
    """Child nodes of ``root`` without descending into nested function
    definitions (used where a nested def is its own scope)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


@register_rule
class TracedCoercionRule(Rule):
    id = "GL002"
    name = "traced-coercion"
    help = ("str()/int()/float()/bool()/f-string applied to a traced "
            "value inside jitted code — host sync + retrace per value")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in collect_jitted(ctx.tree):
            taint = TracedTaint(fn)
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Call) \
                        and call_name(n.func) in _COERCIONS and n.args:
                    if taint.expr_traced(n.args[0]):
                        findings.append(self.finding(
                            ctx, n,
                            f"{call_name(n.func)}() on a traced value "
                            f"inside a jitted function — forces a host "
                            f"sync and retraces per concrete value"))
                elif isinstance(n, ast.JoinedStr):
                    for v in n.values:
                        if isinstance(v, ast.FormattedValue) \
                                and taint.expr_traced(v.value):
                            findings.append(self.finding(
                                ctx, n,
                                "f-string formats a traced value inside "
                                "a jitted function — stringifying a "
                                "tracer bakes in (or crashes on) one "
                                "concrete value"))
                            break
        return findings


@register_rule
class TracedBranchRule(Rule):
    id = "GL003"
    name = "traced-branch"
    help = ("Python if/while/assert/ternary on a traced value inside "
            "jitted code — use jnp.where / jax.lax.cond, or mark the "
            "argument static")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in collect_jitted(ctx.tree):
            taint = TracedTaint(fn)
            for n in ast.walk(fn.node):
                test = None
                kind = ""
                if isinstance(n, ast.If):
                    test, kind = n.test, "if"
                elif isinstance(n, ast.While):
                    test, kind = n.test, "while"
                elif isinstance(n, ast.Assert):
                    test, kind = n.test, "assert"
                elif isinstance(n, ast.IfExp):
                    test, kind = n.test, "ternary"
                if test is not None and taint.expr_traced(test):
                    findings.append(self.finding(
                        ctx, n,
                        f"Python {kind} on a traced value inside a "
                        f"jitted function — branches must be "
                        f"jnp.where/lax.cond (or the argument made "
                        f"static) or tracing specialises per value"))
        return findings


@register_rule
class JitInLoopRule(Rule):
    id = "GL004"
    name = "jit-in-loop"
    help = ("jax.jit constructed inside a loop body — a fresh wrapper "
            "has a fresh trace cache, so hot loops recompile every "
            "iteration; hoist construction out of the loop")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.jit_loop_in_scope(ctx.relpath):
            return []
        findings: List[Finding] = []
        # walk with an explicit loop-depth stack, resetting at function
        # boundaries (a jit built in a def that happens to be defined in
        # a loop runs once per def call, not per loop iteration)
        def visit(node: ast.AST, loop_depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                d = loop_depth
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    d = 0
                elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    d = loop_depth + 1
                if isinstance(child, ast.Call) and is_jax_jit(child.func) \
                        and loop_depth > 0:
                    findings.append(self.finding(
                        ctx, child,
                        "jax.jit(...) constructed inside a loop body — "
                        "every iteration gets a fresh trace cache and "
                        "recompiles; build the jitted callable once "
                        "outside the loop"))
                visit(child, d)
        visit(ctx.tree, 0)
        return findings


@register_rule
class UnhashableStaticRule(Rule):
    id = "GL005"
    name = "unhashable-static"
    help = ("list/dict/set literal passed at a static_argnums/"
            "static_argnames position — static args are trace-cache "
            "keys and must be hashable (use a tuple)")

    _UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                   ast.SetComp)

    def check_file(self, ctx: FileContext) -> List[Finding]:
        # name -> (static positional indices, static kwarg names)
        statics: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for fn in collect_jitted(ctx.tree):
            if not fn.bound_to:
                continue
            pos = fn.positional_params()
            nums = set(fn.static_nums)
            for name in fn.static_names:
                if name in pos:
                    nums.add(pos.index(name))
            if nums or fn.static_names:
                statics[fn.bound_to] = (nums, set(fn.static_names))
        # assignments of jit calls also bind a name: step = jax.jit(f, ...)
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and is_jax_jit(n.value.func):
                call = n.value
                kw = {k.arg: k.value for k in call.keywords if k.arg}
                nums: Set[int] = set()
                names: Set[str] = set()
                for node in ast.walk(kw.get("static_argnums", ast.Pass())):
                    if isinstance(node, ast.Constant) \
                            and isinstance(node.value, int):
                        nums.add(node.value)
                for node in ast.walk(kw.get("static_argnames", ast.Pass())):
                    if isinstance(node, ast.Constant) \
                            and isinstance(node.value, str):
                        names.add(node.value)
                if not nums and not names:
                    continue
                for t in n.targets:
                    key = call_name(t) if isinstance(t, (ast.Attribute,)) \
                        else (t.id if isinstance(t, ast.Name) else "")
                    if key:
                        statics.setdefault(key, (nums, names))
        if not statics:
            return []
        findings: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            key = call_name(n.func)
            if key not in statics:
                continue
            nums, names = statics[key]
            for i, arg in enumerate(n.args):
                if i in nums and isinstance(arg, self._UNHASHABLE):
                    findings.append(self.finding(
                        ctx, arg,
                        f"unhashable literal at static position {i} of "
                        f"{key}() — jit static args are cache keys; "
                        f"pass a tuple"))
            for k in n.keywords:
                if k.arg in names and isinstance(k.value, self._UNHASHABLE):
                    findings.append(self.finding(
                        ctx, k.value,
                        f"unhashable literal for static argument "
                        f"{k.arg!r} of {key}() — jit static args are "
                        f"cache keys; pass a tuple"))
        return findings
