"""GL001 — donated-restore: a donating jit callable may be fed
externally-created arrays.

The motivating incident (PR 2, the seed tier-1 segfault): the trainer's
``_train_step`` donates its state (``donate_argnums=(0,)``); after a
resume, that state held arrays built host-side from a restored
checkpoint (``jax.make_array_from_callback`` over msgpack bytes).
Donating an externally-created array into an executable deserialised
from the persistent compilation cache corrupts the heap on jaxlib
0.4.36 CPU — a segfault far from the cause. The fix is the trainer's
*laundering idiom*: pass restored state through one compiled, undonated
copy (``jax.jit(lambda s: jax.tree.map(jnp.copy, s))``) so the donating
step only ever consumes executable-owned buffers.

This rule does module/class-local taint tracking:

* **sources** — calls whose name looks like deserialisation
  (``restore*``, ``load*``, ``*deserialize*``, ``from_bytes``,
  ``make_array_from_callback``, ``frombuffer``);
* **propagation** — flow-insensitive over ``name`` and ``self.attr``
  assignment keys (if any assignment taints a key, the key is tainted);
* **laundering** — a value returned by an immediately-invoked,
  non-donating ``jax.jit(...)(x)`` call, or by a function whose name
  contains ``launder`` or ``copy``, is clean;
* **sink** — a call through a name bound to ``jax.jit(...,
  donate_argnums=...)`` whose argument at a donated position reads a
  tainted key.

Cross-module flows (serve.py restores, engine donates) are out of
scope by design: the engine only ever donates its own pool cache, and
the rule's job is the same-class pattern that actually bit us.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import (
    call_name, donated_bindings, is_jax_jit, jit_keywords, names_in,
)

_RESTORE_RE = re.compile(
    r"(^|[._])(restore\w*|load\w*|\w*deserialize\w*|from_bytes|"
    r"frombuffer|make_array_from_callback)$")
_LAUNDER_RE = re.compile(r"(launder|copy)", re.IGNORECASE)


def _is_restore_call(node: ast.Call) -> bool:
    return bool(_RESTORE_RE.search(call_name(node.func) or ""))


def _is_laundering_call(node: ast.Call) -> bool:
    """Immediately-invoked undonated jit — ``jax.jit(f, ...)(x)`` — or a
    call into something named like a copy/launder helper."""
    if isinstance(node.func, ast.Call) and is_jax_jit(node.func.func):
        return "donate_argnums" not in jit_keywords(node.func)
    return bool(_LAUNDER_RE.search(call_name(node.func) or ""))


def _target_keys(node: ast.AST) -> Set[str]:
    """Keys an assignment target binds. An attribute target taints ONLY
    its dotted key — ``self.rng = tainted`` must not taint bare ``self``
    (which would transitively taint every ``self.*`` read)."""
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return {f"{node.value.id}.{node.attr}"}
    if isinstance(node, (ast.Tuple, ast.List)):
        keys: Set[str] = set()
        for el in node.elts:
            keys |= _target_keys(el)
        return keys
    if isinstance(node, ast.Starred):
        return _target_keys(node.value)
    if isinstance(node, ast.Subscript):
        # container[k] = tainted taints the container key
        return _target_keys(node.value)
    return set()


class _Region:
    """One taint region: a ClassDef (all methods pooled — restored state
    regularly crosses ``self.*`` between __init__ and the step loop) or
    the module minus its classes."""

    def __init__(self, stmts: List[ast.stmt]):
        self.assigns: List[Tuple[Set[str], ast.AST]] = []
        self.calls: List[ast.Call] = []
        # keys bound to ANY jax.jit(...) — calling through one returns
        # executable-owned buffers, so taint never flows out of it (the
        # step's own output state is exactly what donation is FOR)
        self.jit_bound: Set[str] = set()
        for stmt in stmts:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Assign):
                    tk: Set[str] = set()
                    for t in n.targets:
                        tk |= _target_keys(t)
                    self.assigns.append((tk, n.value))
                    if isinstance(n.value, ast.Call) \
                            and is_jax_jit(n.value.func):
                        self.jit_bound |= tk
                elif isinstance(n, ast.Call):
                    self.calls.append(n)

    def _expr_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        if isinstance(node, ast.Call):
            if _is_laundering_call(node):
                return False
            if call_name(node.func) in self.jit_bound:
                return False
            if _is_restore_call(node):
                return True
            return any(self._expr_tainted(a, tainted) for a in node.args) \
                or any(self._expr_tainted(kw.value, tainted)
                       for kw in node.keywords)
        if isinstance(node, (ast.Name, ast.Attribute)):
            return bool(names_in(node) & tainted)
        return any(self._expr_tainted(c, tainted)
                   for c in ast.iter_child_nodes(node))

    def tainted_keys(self) -> Set[str]:
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for keys, value in self.assigns:
                if keys <= tainted:
                    continue
                if self._expr_tainted(value, tainted):
                    tainted |= keys
                    changed = True
        return tainted

    def expr_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        return self._expr_tainted(node, tainted)


@register_rule
class DonatedRestoreRule(Rule):
    id = "GL001"
    name = "donated-restore"
    help = ("a jit with donate_argnums receives restored/deserialised "
            "arrays that never passed through a compiled undonated copy "
            "(the PR 2 resume-segfault class)")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        donors: Dict[str, Tuple[ast.Call, Set[int]]] = \
            donated_bindings(ctx.tree)
        if not donors:
            return []
        regions: List[List[ast.stmt]] = []
        module_stmts: List[ast.stmt] = []
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.ClassDef):
                regions.append(stmt.body)
            else:
                module_stmts.append(stmt)
        regions.append(module_stmts)

        findings: List[Finding] = []
        for stmts in regions:
            region = _Region(stmts)
            tainted = region.tainted_keys()
            if not tainted:
                continue
            for call in region.calls:
                key = call_name(call.func)
                if key not in donors:
                    continue
                _, donated_positions = donors[key]
                for pos in sorted(donated_positions):
                    if pos >= len(call.args):
                        continue
                    arg = call.args[pos]
                    if region.expr_tainted(arg, tainted):
                        hot = sorted(names_in(arg) & tainted) or ["<expr>"]
                        findings.append(self.finding(
                            ctx, call,
                            f"donated argument {pos} of {key}() may hold "
                            f"restored/deserialised arrays ({', '.join(hot)}) "
                            f"— launder through a compiled undonated copy "
                            f"first (jax.jit(lambda s: jax.tree.map("
                            f"jnp.copy, s)))"))
        return findings
