"""GL007 — wall-clock: direct ``time.*`` calls in clock-disciplined
paths.

The motivating incident (PR 5): the serving chaos harness is only
deterministic — crash/skew tests token-identical, zero wall sleeps —
because every scheduling, deadline, backoff and health decision reads
an injectable clock (``VirtualClock``/``WallClock``/``SkewedClock`` in
``serving/fleet.py``, the ``clock=`` ctor parameter in the scheduler,
``RetryPolicy.sleep`` in durability). One stray ``time.time()`` in a
scheduling decision and the chaos tests either flake or quietly stop
testing what they claim.

Within the scoped paths (``serving/``, ``training/faults.py``), flag
calls to ``time.time``/``time.sleep``/``time.monotonic``/
``time.perf_counter`` (including ``from time import sleep`` aliases),
except:

* inside a class whose name ends in ``Clock`` — that IS the
  abstraction (``WallClock.now`` must read the real clock somewhere);
* ``time.time()`` whose result is bound to a telemetry-timestamp name
  (``ts``, ``timestamp``, ``*_ts``, ``*_timestamp``) — epoch stamps on
  exported records are data, not control flow, and must NOT follow the
  virtual clock (a skewed export timestamp would corrupt real
  telemetry).

References to the functions (``sleep: Callable = time.sleep`` as an
injectable default) are fine — the rule flags *calls*, which is exactly
the line ``durability.RetryPolicy`` already walks.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)

_WALL_FNS = {"time", "sleep", "monotonic", "perf_counter",
             "monotonic_ns", "perf_counter_ns", "time_ns"}


def _wall_call(node: ast.Call, time_aliases: Dict[str, str]) -> Optional[str]:
    """"time.sleep" when this call hits the wall clock, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "time" and f.attr in _WALL_FNS:
            return f"time.{f.attr}"
    if isinstance(f, ast.Name) and f.id in time_aliases:
        return time_aliases[f.id]
    return None


def _time_aliases(tree: ast.Module) -> Dict[str, str]:
    """``from time import sleep as zzz`` -> {"zzz": "time.sleep"}."""
    out: Dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            for a in n.names:
                if a.name in _WALL_FNS:
                    out[a.asname or a.name] = f"time.{a.name}"
    return out


@register_rule
class WallClockRule(Rule):
    id = "GL007"
    name = "wall-clock"
    help = ("direct time.time/sleep/monotonic/perf_counter call in a "
            "clock-disciplined path — inject the Clock abstraction so "
            "chaos/fault tests stay deterministic and sleep-free")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.clock_in_scope(ctx.relpath):
            return []
        aliases = _time_aliases(ctx.tree)
        findings: List[Finding] = []

        def visit(node: ast.AST, in_clock_class: bool,
                  assign_names: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                clock = in_clock_class
                names = assign_names
                if isinstance(child, ast.ClassDef):
                    clock = child.name.endswith("Clock")
                if isinstance(child, (ast.Assign, ast.AnnAssign,
                                      ast.AugAssign)):
                    targets = child.targets if isinstance(child, ast.Assign) \
                        else [child.target]
                    collected: List[str] = []
                    for t in targets:
                        for el in ast.walk(t):
                            if isinstance(el, ast.Name):
                                collected.append(el.id)
                            elif isinstance(el, ast.Attribute):
                                collected.append(el.attr)
                    names = tuple(collected)
                if isinstance(child, ast.Call) and not clock:
                    hit = _wall_call(child, aliases)
                    if hit is not None:
                        ts_ok = (hit == "time.time" and any(
                            ctx.config.clock_ts_allowed(nm) for nm in names))
                        if not ts_ok:
                            findings.append(self.finding(
                                ctx, child,
                                f"{hit}() called directly in a "
                                f"clock-disciplined path — take an "
                                f"injectable clock/sleep (see "
                                f"serving/fleet.py clocks, "
                                f"durability.RetryPolicy.sleep)"))
                visit(child, clock, names)

        visit(ctx.tree, False, ())
        return findings
