"""GL011 — trailing-none-spec: a ``PartitionSpec`` authored with
trailing ``None`` entries.

The motivating incident (PR 12): the runtime normalizes sharding specs
by stripping trailing ``None``s, and compiled-program outputs carry the
normalized form — but executable caches and the serving engine key on
sharding *equality*. A warmup cache placed under
``PartitionSpec("tp", None)`` therefore compares unequal to the
``PartitionSpec("tp")`` the first compiled call returns, and the next
call on a "warmed" bucket silently compiles a second, identical
executable. The fix is to never author the trailing ``None``: an
unmentioned dimension already means replicated, so
``P("pp", None)`` and ``P("pp")`` place identically — only the
spelled form breaks equality. graftaudit (``analysis/hlo_audit.py``)
checks the same invariant on the lowered artifacts; this rule catches
it at review time.

``P(None, "tp")`` is fine (the ``None`` is load-bearing: it positions
``"tp"`` on a later dimension). ``P(None)`` and ``P(None, None)`` are
just ``P()`` with extra steps, and are flagged.
"""

from __future__ import annotations

import ast
from typing import List

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import call_name


@register_rule
class TrailingNoneSpecRule(Rule):
    id = "GL011"
    name = "trailing-none-spec"
    help = ("PartitionSpec authored with a trailing None — the runtime "
            "strips it during normalization, so equality-keyed caches "
            "see a novel sharding; drop it (unmentioned dims replicate)")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            fname = call_name(n.func)
            if not fname:
                continue
            if fname != "P" and fname.split(".")[-1] != "PartitionSpec":
                continue
            last = n.args[-1]
            if isinstance(last, ast.Constant) and last.value is None:
                findings.append(self.finding(
                    ctx, n,
                    "PartitionSpec with a trailing None — the runtime "
                    "normalizes it away and sharding-equality caches "
                    "then mismatch (PR 12: spurious executables); drop "
                    "the trailing None"))
        return findings
