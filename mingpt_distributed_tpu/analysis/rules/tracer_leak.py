"""GL006 — tracer-leak: traced values escaping a jitted function
through ``self``/globals.

A tracer stored on ``self`` or a module-level container during tracing
outlives the trace: the *first* call writes a tracer object (not an
array) into long-lived host state, and every later read either crashes
with the infamous ``UnexpectedTracerError`` or — when the slot is only
read under another trace — silently freezes the first call's value.
The serving engine keeps all cross-step state in explicit carry values
(cache in, cache out) precisely to avoid this; this rule makes that
discipline checkable.

Flagged inside jitted code:

* ``self.<attr> = <expr reading a traced value>`` (and ``+=`` etc.);
* ``global``/``nonlocal`` declarations (a traced function mutating
  outer scope is the same escape with fewer steps);
* subscript stores into names not local to the jitted function
  (``CACHE[k] = traced``).
"""

from __future__ import annotations

import ast
from typing import List, Set

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import TracedTaint, collect_jitted


def _local_names(fn_node: ast.AST) -> Set[str]:
    """Names assigned (or bound as params) anywhere inside the function
    — stores into anything else leave the trace."""
    out: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(n.name)
            a = n.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                out.add(p.arg)
            if a.vararg:
                out.add(a.vararg.arg)
            if a.kwarg:
                out.add(a.kwarg.arg)
        elif isinstance(n, ast.Lambda):
            a = n.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                out.add(p.arg)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for el in ast.walk(n.target):
                if isinstance(el, ast.Name):
                    out.add(el.id)
    if isinstance(fn_node, ast.Lambda):
        a = fn_node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            out.add(p.arg)
    return out


@register_rule
class TracerLeakRule(Rule):
    id = "GL006"
    name = "tracer-leak"
    help = ("a traced value is stored to self./globals from inside a "
            "jitted function — tracers must never outlive their trace; "
            "return the value through the function's outputs")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in collect_jitted(ctx.tree):
            taint = TracedTaint(fn)
            locals_ = _local_names(fn.node)
            for n in ast.walk(fn.node):
                if isinstance(n, (ast.Global, ast.Nonlocal)):
                    findings.append(self.finding(
                        ctx, n,
                        f"{'global' if isinstance(n, ast.Global) else 'nonlocal'} "
                        f"declaration inside a jitted function — traced "
                        f"code must not mutate outer scope"))
                    continue
                targets: List[ast.AST] = []
                value: ast.AST = None
                if isinstance(n, ast.Assign):
                    targets, value = n.targets, n.value
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    targets = [n.target]
                    value = n.value if n.value is not None else None
                if not targets or value is None \
                        or not taint.expr_traced(value):
                    continue
                for t in targets:
                    leak = None
                    if isinstance(t, ast.Attribute):
                        base = t.value
                        if isinstance(base, ast.Name) \
                                and base.id not in locals_ - {"self"}:
                            leak = f"{base.id}.{t.attr}"
                    elif isinstance(t, ast.Subscript):
                        base = t.value
                        if isinstance(base, ast.Name) \
                                and base.id not in locals_:
                            leak = f"{base.id}[...]"
                    if leak:
                        findings.append(self.finding(
                            ctx, n,
                            f"traced value stored to {leak} inside a "
                            f"jitted function — the tracer outlives its "
                            f"trace (UnexpectedTracerError or a frozen "
                            f"first-call value); thread it through the "
                            f"return value instead"))
        return findings
