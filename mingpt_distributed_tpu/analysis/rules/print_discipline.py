"""GL010 — bare-print: library code prints without going through
``telemetry.spans.log_event``.

The motivating incident (PR 4): unifying observability meant hunting
down every ad-hoc ``print`` in the stack — on a pod, an unprefixed line
from 32 processes is unattributable noise, and anything printed outside
``log_event`` never reaches the span ring or the JSONL sink, so the
flight recorder has holes exactly where someone thought a message
mattered enough to print.

Scope: library paths only (``mingpt_distributed_tpu/``). CLIs
(``train.py``, ``serve.py``, ``tools/``) print to their user by design
and are out of scope, as is ``telemetry/spans.py`` itself (something
has to own the actual ``print``). ``sys.stdout.write``/
``sys.stderr.write`` count too — they are the same hole with a
different spelling.
"""

from __future__ import annotations

import ast
from typing import List

from mingpt_distributed_tpu.analysis.core import (
    FileContext, Finding, Rule, register_rule,
)
from mingpt_distributed_tpu.analysis.jitutil import call_name


@register_rule
class BarePrintRule(Rule):
    id = "GL010"
    name = "bare-print"
    help = ("bare print() in library code — route through "
            "telemetry.spans.log_event so the line is process-prefixed "
            "and mirrored into the span ring/JSONL")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.print_in_scope(ctx.relpath):
            return []
        findings: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            fname = call_name(n.func)
            if fname == "print":
                findings.append(self.finding(
                    ctx, n,
                    "bare print() in library code — use "
                    "telemetry.spans.log_event (process-prefixed, "
                    "mirrored to the span ring and JSONL sink)"))
            elif fname in ("sys.stdout.write", "sys.stderr.write"):
                findings.append(self.finding(
                    ctx, n,
                    f"{fname}() in library code — same hole as bare "
                    f"print(); use telemetry.spans.log_event"))
        return findings
