"""graftlint core: findings, the rule registry, scoping config,
inline suppressions, and the baseline file format.

Design constraints that shaped this module:

* **Pure ``ast``** — rules receive a parsed tree + source lines, never
  an imported module. Analysing ``serving/engine.py`` must not compile
  a decode program (or worse, dial an accelerator from CI).
* **Stable IDs** — every rule owns a ``GLxxx`` ID that appears in
  suppression comments and baseline entries; renaming a rule class must
  never invalidate either, so the ID (not the class name) is the key.
* **Deterministic output** — findings sort by (path, line, col, id);
  two runs over the same tree produce byte-identical reports, which is
  what lets ``run_tests.sh`` gate on the exit code.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA = "graftlint/1"
BASELINE_SCHEMA = "graftlint-baseline/1"

#: exit codes (documented in docs/static_analysis.md — consumers key on
#: these, keep them stable)
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2


# ---------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule_id: str
    rule_name: str
    path: str                 # repo-relative posix path
    line: int                 # 1-based
    col: int                  # 0-based (ast convention)
    message: str
    end_line: int = 0         # last physical line of the flagged node
    source: str = ""          # stripped text of the flagged line
    suppressed: bool = False  # inline `# graftlint: disable=`
    baselined: bool = False   # matched a baseline entry

    def __post_init__(self):
        if not self.end_line:
            self.end_line = self.line

    @property
    def active(self) -> bool:
        """True when this finding should fail the gate."""
        return not (self.suppressed or self.baselined)

    def sort_key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule_id)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "name": self.rule_name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "source": self.source,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule_id} [{self.rule_name}] {self.message}")


# ---------------------------------------------------------------------
# Scoping config
# ---------------------------------------------------------------------


def _match_any(relpath: str, patterns: Sequence[str]) -> bool:
    """Substring match against a posix relpath — ``"serving/"`` matches
    every file under any ``serving`` directory; a full filename pattern
    like ``"training/faults.py"`` matches exactly that module."""
    return any(p in relpath for p in patterns)


@dataclass
class Config:
    """Per-rule path scopes and allowlists.

    Defaults encode THIS repo's layout; the fixture tests pass custom
    scopes so the corpus under ``tests/lint_fixtures/`` exercises every
    rule without having to mimic the production tree.
    """

    # GL007: paths where wall-clock calls must go through the Clock
    # abstraction (serving chaos harness + fault injector are only
    # deterministic because of it; request tracing and the flight
    # recorder take every timestamp from an injected clock so the
    # chaos-gate trace assertions stay exact; the traffic lab's load
    # sweeps are byte-replayable only because arrival schedules are
    # virtual-timestamp data and the runner never reads a wall clock)
    clock_paths: Tuple[str, ...] = (
        "serving/",
        # redundant with serving/ by prefix, but pinned explicitly: the
        # procfleet chaos suite is sleep-free ONLY because process-level
        # faults land as clock skew / raised verdicts, never wall sleeps
        # (socket timeouts are connection attributes, not time.* calls,
        # and stay allowed)
        "serving/procfleet/",
        # likewise pinned outright: heartbeat deadlines, the token-bucket
        # pacing budget, and transfer retry backoff all live on the
        # injected clock — the module imports no `time` at all, which
        # the hostplane pin test asserts
        "serving/procfleet/hostplane.py",
        "training/faults.py",
        "telemetry/tracing.py",
        "telemetry/flightrec.py",
        "telemetry/attribution.py",
        "trafficlab/",
        # the control plane decides *when* to scale from ControlSnapshot
        # timestamps sampled off the router's injected clock; a stray
        # time.time() in the governor would make autoscaled sweeps
        # non-replayable, so the whole package is in scope
        "control/",
    )
    # GL007: time.time() results bound to these names are telemetry
    # timestamps (epoch stamps on records), not scheduling decisions
    clock_ts_names: Tuple[str, ...] = (
        r"^ts$", r"^timestamp$", r".*_ts$", r".*_timestamp$",
    )
    # GL010: library paths where bare print() is banned (CLIs print by
    # design; the library logs through telemetry.spans.log_event)
    print_paths: Tuple[str, ...] = ("mingpt_distributed_tpu/",)
    # GL010: the log_event implementation itself, and any other module
    # whose job is to print
    print_exempt_paths: Tuple[str, ...] = (
        "mingpt_distributed_tpu/analysis/",   # lint reports go to stdout
        "telemetry/spans.py",                 # log_event's own print
    )
    # GL004: compile-behaviour experiment scripts construct jits in
    # loops on purpose (they measure exactly that)
    jit_loop_exempt_paths: Tuple[str, ...] = ("tools/exp_", "tools/proto_")

    def clock_in_scope(self, relpath: str) -> bool:
        return _match_any(relpath, self.clock_paths)

    def clock_ts_allowed(self, name: str) -> bool:
        return any(re.match(p, name) for p in self.clock_ts_names)

    def print_in_scope(self, relpath: str) -> bool:
        return (_match_any(relpath, self.print_paths)
                and not _match_any(relpath, self.print_exempt_paths))

    def jit_loop_in_scope(self, relpath: str) -> bool:
        return not _match_any(relpath, self.jit_loop_exempt_paths)


# ---------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------


class Rule:
    """Base class. Subclasses set ``id``/``name``/``help`` and override
    ``check_file``; rules needing cross-file state accumulate it across
    ``check_file`` calls and emit in ``finalize`` (the engine
    instantiates a fresh rule object per run, so state never leaks
    between runs)."""

    id: str = ""
    name: str = ""
    help: str = ""

    def check_file(self, ctx: "FileContext") -> List[Finding]:
        return []

    def finalize(self) -> List[Finding]:
        return []

    # -- helpers shared by every rule ----------------------------------
    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule_id=self.id,
            rule_name=self.name,
            path=ctx.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            end_line=getattr(node, "end_lineno", line) or line,
            message=message,
            source=ctx.line_text(line),
        )


@dataclass
class FileContext:
    """Everything a rule sees for one file."""

    relpath: str
    tree: ast.Module
    lines: List[str]
    config: Config

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


_RULES: Dict[str, type] = {}
_ID_RE = re.compile(r"^GL\d{3}$")


def register_rule(cls: type) -> type:
    """Class decorator adding a rule to the global registry. IDs are
    claimed forever: re-registering an ID with a different class is a
    programming error, not a merge strategy."""
    if not _ID_RE.match(getattr(cls, "id", "")):
        raise ValueError(f"rule {cls.__name__} needs an id matching GLxxx")
    prev = _RULES.get(cls.id)
    if prev is not None and prev is not cls:
        raise ValueError(
            f"rule id {cls.id} already registered by {prev.__name__}")
    _RULES[cls.id] = cls
    return cls


def all_rules() -> List[type]:
    """Registered rule classes, by ID (import side effect: registers)."""
    import mingpt_distributed_tpu.analysis.rules  # noqa: F401
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str) -> type:
    import mingpt_distributed_tpu.analysis.rules  # noqa: F401
    return _RULES[rule_id]


# ---------------------------------------------------------------------
# Inline suppressions
# ---------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-next|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\s]+)")


class Suppressions:
    """Parsed ``# graftlint:`` comments for one file.

    * ``disable=GL001[,GL002]`` — suppresses findings whose flagged node
      touches that physical line;
    * ``disable-next=GL001`` — suppresses findings starting on the next
      line (for statements where a trailing comment won't fit);
    * ``disable-file=GL001`` — suppresses the rule for the whole file
      (only honoured in the first 20 lines, next to the docstring, so a
      reviewer can't miss it).

    ``all`` is accepted in place of an ID list.
    """

    def __init__(self, lines: Sequence[str]):
        self.on_line: Dict[int, set] = {}
        self.next_line: Dict[int, set] = {}
        self.whole_file: set = set()
        for i, text in enumerate(lines, start=1):
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            kind = m.group(1)
            ids = {t.strip().upper() for t in m.group(2).split(",") if t.strip()}
            if kind == "disable":
                self.on_line.setdefault(i, set()).update(ids)
            elif kind == "disable-next":
                self.next_line.setdefault(i + 1, set()).update(ids)
            elif kind == "disable-file" and i <= 20:
                self.whole_file.update(ids)

    def _hit(self, ids: set, rule_id: str) -> bool:
        return rule_id in ids or "ALL" in ids

    def covers(self, f: Finding) -> bool:
        if self._hit(self.whole_file, f.rule_id):
            return True
        if self._hit(self.next_line.get(f.line, set()), f.rule_id):
            return True
        # a trailing comment anywhere on the flagged statement counts —
        # multi-line calls put it wherever black leaves room
        for ln in range(f.line, f.end_line + 1):
            if self._hit(self.on_line.get(ln, set()), f.rule_id):
                return True
        return False


# ---------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------


@dataclass
class BaselineEntry:
    rule: str
    path: str            # repo-relative posix path (suffix-matched)
    contains: str        # substring of the flagged source line
    justification: str   # required — an unexplained grandfather rots

    def matches(self, f: Finding) -> bool:
        return (f.rule_id == self.rule
                and (f.path == self.path or f.path.endswith("/" + self.path))
                and self.contains in f.source)


@dataclass
class Baseline:
    """Checked-in grandfathered findings. Matching is content-anchored
    (rule, path, line *text*) rather than line-numbered, so unrelated
    edits above a grandfathered site don't invalidate the baseline."""

    entries: List[BaselineEntry] = field(default_factory=list)
    path: Optional[str] = None

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            raw = json.load(fh)
        if raw.get("schema") != BASELINE_SCHEMA:
            raise ValueError(
                f"{path}: baseline schema {raw.get('schema')!r} != "
                f"{BASELINE_SCHEMA!r}")
        entries = []
        for e in raw.get("entries", []):
            missing = {"rule", "path", "contains", "justification"} - set(e)
            if missing:
                raise ValueError(
                    f"{path}: baseline entry {e!r} missing {sorted(missing)}")
            entries.append(BaselineEntry(
                rule=e["rule"], path=e["path"], contains=e["contains"],
                justification=e["justification"]))
        return cls(entries=entries, path=path)

    def apply(self, findings: List[Finding]) -> List[BaselineEntry]:
        """Mark matching findings baselined; return entries that matched
        nothing (stale — the violation was fixed, prune the entry)."""
        used = [False] * len(self.entries)
        for f in findings:
            if f.suppressed:
                continue
            for i, e in enumerate(self.entries):
                if e.matches(f):
                    f.baselined = True
                    used[i] = True
                    break
        return [e for i, e in enumerate(self.entries) if not used[i]]
