"""graftlint engine: file collection, rule execution, suppression and
baseline application, human/JSON rendering.

The engine never imports analysed code — everything is ``ast.parse``
over file bytes, so linting ``serving/engine.py`` cannot initialise a
JAX backend, and the gate runs in well under a second on this repo.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from mingpt_distributed_tpu.analysis.core import (
    SCHEMA,
    Baseline,
    BaselineEntry,
    Config,
    FileContext,
    Finding,
    Suppressions,
    all_rules,
)

#: directories never descended into (fixtures deliberately violate
#: every rule — sweeping them would be the lint linting its own tests)
EXCLUDE_DIRS = {
    "__pycache__", ".git", ".jax_test_cache", ".venv", "node_modules",
    "build", "dist", ".eggs", "lint_fixtures",
}


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into a sorted, de-duplicated .py file list.
    Explicitly named files are always included (that is how the fixture
    tests lint the corpus EXCLUDE_DIRS hides from sweeps)."""
    out: List[str] = []
    seen = set()

    def add(p: str) -> None:
        rp = os.path.realpath(p)
        if rp not in seen:
            seen.add(rp)
            out.append(p)

    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                add(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIRS)
            for f in sorted(files):
                if f.endswith(".py"):
                    add(os.path.join(root, f))
    return sorted(out)


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)
    stale_baseline: List[BaselineEntry] = field(default_factory=list)

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if f.active]

    @property
    def suppressed_count(self) -> int:
        return sum(1 for f in self.findings if f.suppressed)

    @property
    def baselined_count(self) -> int:
        return sum(1 for f in self.findings if f.baselined)

    @property
    def exit_code(self) -> int:
        return 1 if (self.active or self.parse_errors) else 0

    # -- rendering -----------------------------------------------------
    def to_json(self) -> dict:
        per_rule: Dict[str, int] = {}
        for f in self.active:
            per_rule[f.rule_id] = per_rule.get(f.rule_id, 0) + 1
        return {
            "schema": SCHEMA,
            "summary": {
                "files": self.files_scanned,
                "findings": len(self.active),
                "suppressed": self.suppressed_count,
                "baselined": self.baselined_count,
                "parse_errors": list(self.parse_errors),
                "per_rule": dict(sorted(per_rule.items())),
                "stale_baseline": [e.__dict__ for e in self.stale_baseline],
            },
            "findings": [f.to_dict() for f in self.findings],
        }

    def render_human(self, show_suppressed: bool = False) -> str:
        lines: List[str] = []
        for err in self.parse_errors:
            lines.append(f"error: {err}")
        for f in self.findings:
            if f.active:
                lines.append(f.render())
            elif show_suppressed:
                tag = "suppressed" if f.suppressed else "baselined"
                lines.append(f"{f.render()}  [{tag}]")
        for e in self.stale_baseline:
            lines.append(
                f"warning: stale baseline entry {e.rule} {e.path} "
                f"({e.contains!r}) matched nothing — prune it")
        n = len(self.active)
        lines.append(
            f"graftlint: {self.files_scanned} files, "
            f"{n} finding{'s' if n != 1 else ''} "
            f"({self.suppressed_count} suppressed, "
            f"{self.baselined_count} baselined)")
        return "\n".join(lines)


class Engine:
    """One lint run: fresh rule instances, deterministic output."""

    def __init__(
        self,
        config: Optional[Config] = None,
        baseline: Optional[Baseline] = None,
        select: Optional[Sequence[str]] = None,
        root: Optional[str] = None,
    ):
        self.config = config or Config()
        self.baseline = baseline
        self.root = os.path.realpath(root or os.getcwd())
        rules = all_rules()
        if select:
            wanted = {s.upper() for s in select}
            unknown = wanted - {r.id for r in rules}
            if unknown:
                raise ValueError(f"unknown rule ids: {sorted(unknown)}")
            rules = [r for r in rules if r.id in wanted]
        self.rules = [cls() for cls in rules]

    def _relpath(self, path: str) -> str:
        rp = os.path.realpath(path)
        if rp.startswith(self.root + os.sep):
            rp = rp[len(self.root) + 1:]
        return rp.replace(os.sep, "/")

    def run(self, paths: Sequence[str]) -> RunResult:
        result = RunResult()
        suppressions: Dict[str, Suppressions] = {}
        for path in collect_files(paths):
            relpath = self._relpath(path)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    src = fh.read()
                tree = ast.parse(src, filename=path)
            except (OSError, SyntaxError, ValueError) as e:
                result.parse_errors.append(f"{relpath}: {e}")
                continue
            result.files_scanned += 1
            lines = src.splitlines()
            suppressions[relpath] = Suppressions(lines)
            ctx = FileContext(relpath=relpath, tree=tree, lines=lines,
                              config=self.config)
            for rule in self.rules:
                result.findings.extend(rule.check_file(ctx))
        for rule in self.rules:
            result.findings.extend(rule.finalize())
        # suppressions, then baseline (a suppressed finding never
        # consumes a baseline entry), then deterministic order
        for f in result.findings:
            sup = suppressions.get(f.path)
            if sup is not None and sup.covers(f):
                f.suppressed = True
        if self.baseline is not None:
            result.stale_baseline = self.baseline.apply(result.findings)
        result.findings.sort(key=Finding.sort_key)
        return result
