"""graftlint CLI.

Exit codes (stable, gate on them):
  0  no unsuppressed, unbaselined findings
  1  findings (or unparseable source)
  2  usage error (unknown rule id, unreadable baseline)

``--json`` emits the ``graftlint/1`` envelope on stdout — the same
"versioned schema on one line of contract" idiom as the telemetry JSONL
export, so ``tools/trace_summary.py``-style consumers can ingest
findings without screen-scraping the human report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from mingpt_distributed_tpu.analysis.core import (
    EXIT_USAGE, Baseline, all_rules,
)
from mingpt_distributed_tpu.analysis.engine import Engine

DEFAULT_BASELINE = "lint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mingpt_distributed_tpu.analysis",
        description="graftlint: repo-specific JAX-aware static analysis "
                    "(rule catalog: docs/static_analysis.md)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: "
                        "mingpt_distributed_tpu tools *.py)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the graftlint/1 JSON envelope")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: ./{DEFAULT_BASELINE} "
                        f"when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--select", default=None, metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed/baselined findings in the "
                        "human report")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def default_paths() -> List[str]:
    """The repo sweep: the package, tools/, and top-level scripts."""
    out = []
    for p in ("mingpt_distributed_tpu", "tools"):
        if os.path.isdir(p):
            out.append(p)
    out.extend(sorted(
        f for f in os.listdir(".")
        if f.endswith(".py") and os.path.isfile(f)))
    return out or ["."]


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for cls in all_rules():
            print(f"{cls.id}  {cls.name:<18} {cls.help}")
        return 0

    baseline = None
    if not args.no_baseline:
        path = args.baseline or (
            DEFAULT_BASELINE if os.path.isfile(DEFAULT_BASELINE) else None)
        if path is not None:
            try:
                baseline = Baseline.load(path)
            except (OSError, ValueError) as e:
                print(f"graftlint: bad baseline: {e}", file=sys.stderr)
                return EXIT_USAGE

    select = [s for s in (args.select or "").split(",") if s.strip()] or None
    try:
        engine = Engine(baseline=baseline, select=select)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return EXIT_USAGE

    result = engine.run(args.paths or default_paths())
    if args.as_json:
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
    else:
        print(result.render_human(show_suppressed=args.show_suppressed))
    return result.exit_code
