"""Shared JAX-awareness for graftlint rules: which functions in a module
get jitted, which of their parameters are static, and which expressions
are traced values.

The resolution is deliberately module-local and name-based:

* a ``FunctionDef``/``Lambda`` is *jitted* when it is decorated with
  ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``, or its name (or
  the lambda itself, or a ``functools.partial(name, ...)`` wrapper over
  its name) is passed to a ``jax.jit(...)`` call anywhere in the same
  module;
* parameters named in ``static_argnames`` or indexed by
  ``static_argnums`` are *static* — branching or string-formatting on
  them re-traces by design and is not a finding;
* keyword arguments bound by a ``functools.partial`` wrapper are
  treated as static too (``partial(_prefill_impl, cfg=cfg)`` makes
  ``cfg`` a closure constant of the trace, exactly like a static
  argname).

Factory-made steps (``jax.jit(make_train_step(cfg, ...))``) are *not*
resolved — the jitted callable is the return value of a call, and
chasing it would need real interprocedural analysis for marginal gain:
every factory in this repo returns a closure whose body is covered the
day it's decorated directly. Fewer false positives beats fake recall.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple, Union

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

#: attribute reads on a traced array that yield trace-time-concrete
#: Python values (shapes are static under jit) — taint stops here
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "aval"}


def call_name(node: ast.AST) -> str:
    """Dotted name of a call target: ``jax.jit`` -> "jax.jit",
    ``self._f`` -> "self._f"; "" when not a simple dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` (from ``jax import jit``)."""
    return call_name(node) in ("jax.jit", "jit")


def is_partial(node: ast.AST) -> bool:
    return call_name(node) in ("functools.partial", "partial")


def _const_strs(node: ast.AST) -> Set[str]:
    """String constants inside a tuple/list/constant node."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def _const_ints(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int):
            out.add(n.value)
    return out


def jit_keywords(call: ast.Call) -> Dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


@dataclass
class JittedFn:
    """One function that will be traced, with its staticness facts."""

    node: FuncNode
    static_names: Set[str] = field(default_factory=set)
    static_nums: Set[int] = field(default_factory=set)
    partial_bound: Set[str] = field(default_factory=set)
    donate_nums: Set[int] = field(default_factory=set)
    donate_names: Set[str] = field(default_factory=set)
    bound_to: str = ""        # "self._train_step" / "step_fn" / ""
    jit_call: Optional[ast.Call] = None

    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def positional_params(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def traced_params(self) -> Set[str]:
        pos = self.positional_params()
        static = set(self.static_names) | set(self.partial_bound)
        for i in sorted(self.static_nums):
            if 0 <= i < len(pos):
                static.add(pos[i])
        return {p for p in self.params() if p not in static}

    def donated_params(self) -> Set[str]:
        pos = self.positional_params()
        out = set(self.donate_names)
        for i in sorted(self.donate_nums):
            if 0 <= i < len(pos):
                out.add(pos[i])
        return out


def _apply_jit_kwargs(fn: JittedFn, call: ast.Call) -> None:
    kw = jit_keywords(call)
    if "static_argnames" in kw:
        fn.static_names |= _const_strs(kw["static_argnames"])
    if "static_argnums" in kw:
        fn.static_nums |= _const_ints(kw["static_argnums"])
    if "donate_argnums" in kw:
        fn.donate_nums |= _const_ints(kw["donate_argnums"])
    if "donate_argnames" in kw:
        fn.donate_names |= _const_strs(kw["donate_argnames"])


def _assign_target_key(node: ast.AST) -> str:
    """ "name" / "self.attr" keys for taint + callable tracking."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _assign_target_key(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def collect_jitted(tree: ast.Module) -> List[JittedFn]:
    """Every jitted function resolvable within this module."""
    # name -> def node, innermost-last so later defs shadow earlier ones
    defs: Dict[str, FuncNode] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[n.name] = n

    out: List[JittedFn] = []
    seen: Set[int] = set()

    def add(node: FuncNode, call: Optional[ast.Call],
            bound_to: str = "") -> JittedFn:
        fn = JittedFn(node=node, bound_to=bound_to, jit_call=call)
        if call is not None:
            _apply_jit_kwargs(fn, call)
        out.append(fn)
        seen.add(id(node))
        return fn

    # 1) decorators
    for n in ast.walk(tree):
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in n.decorator_list:
            if is_jax_jit(dec):
                add(n, None, bound_to=n.name)
            elif (isinstance(dec, ast.Call) and is_partial(dec)
                    and dec.args and is_jax_jit(dec.args[0])):
                add(n, dec, bound_to=n.name)
            elif isinstance(dec, ast.Call) and is_jax_jit(dec.func):
                add(n, dec, bound_to=n.name)

    # 2) jax.jit(<target>, ...) calls
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and is_jax_jit(n.func) and n.args):
            continue
        target = n.args[0]
        partial_bound: Set[str] = set()
        if isinstance(target, ast.Call) and is_partial(target) and target.args:
            partial_bound = {kw.arg for kw in target.keywords if kw.arg}
            target = target.args[0]
        node: Optional[FuncNode] = None
        if isinstance(target, ast.Lambda):
            node = target
        elif isinstance(target, ast.Name):
            node = defs.get(target.id)
        if node is None or id(node) in seen:
            # still record kwargs for an already-seen def (a second jit
            # wrapper over the same fn, e.g. sliding vs cached generate)
            if node is not None:
                for fn in out:
                    if fn.node is node:
                        _apply_jit_kwargs(fn, n)
            continue
        fn = add(node, n)
        fn.partial_bound = partial_bound
    return out


def donated_bindings(tree: ast.Module) -> Dict[str, Tuple[ast.Call, Set[int]]]:
    """Assignments binding a donating jit to a name:
    ``self._step = jax.jit(..., donate_argnums=(0,))`` ->
    {"self._step": (call, {0})}. Keys are later matched against call
    sites by the donation rule."""
    out: Dict[str, Tuple[ast.Call, Set[int]]] = {}
    for n in ast.walk(tree):
        if not isinstance(n, ast.Assign) or not isinstance(n.value, ast.Call):
            continue
        call = n.value
        if not is_jax_jit(call.func):
            continue
        kw = jit_keywords(call)
        if "donate_argnums" not in kw and "donate_argnames" not in kw:
            continue
        nums = _const_ints(kw["donate_argnums"]) if "donate_argnums" in kw \
            else set()
        for t in n.targets:
            key = _assign_target_key(t)
            if key:
                out[key] = (call, nums)
    return out


def names_in(node: ast.AST) -> Set[str]:
    """All dotted-name keys an expression *reads*: {"x", "self.state",
    "self"} for ``f(x, self.state)`` — attribute chains contribute both
    the full key and their base name."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            key = _assign_target_key(n)
            if key:
                out.add(key)
    return out


class TracedTaint:
    """Which local names hold traced values inside one jitted function.

    Seeds: the non-static parameters. Propagation: a simple fixpoint
    over ``Assign``/``AugAssign`` — a target becomes traced when its RHS
    reads a traced name, EXCEPT through the static attribute ring
    (``x.shape``/``x.dtype``…) and ``len()``, which are concrete at
    trace time. Nested ``def``s (scan/cond bodies) contribute their own
    params as traced.
    """

    def __init__(self, fn: JittedFn):
        self.traced: Set[str] = set(fn.traced_params())
        body = fn.node.body if isinstance(fn.node.body, list) \
            else [fn.node.body]
        for sub in ast.walk(fn.node):
            if sub is fn.node:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                a = sub.args
                for p in a.posonlyargs + a.args + a.kwonlyargs:
                    self.traced.add(p.arg)
        changed = True
        while changed:
            changed = False
            for stmt in ast.walk(fn.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AugAssign):
                    targets, value = [stmt.target], stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    targets, value = [stmt.target], stmt.value
                if value is None or not self.expr_traced(value):
                    continue
                for t in targets:
                    for el in ast.walk(t):
                        if isinstance(el, ast.Name) \
                                and el.id not in self.traced:
                            self.traced.add(el.id)
                            changed = True
        del body

    def expr_traced(self, node: ast.AST) -> bool:
        """Does this expression (transitively) read a traced value —
        without passing through a shape/dtype escape hatch?"""
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_traced(node.value)
        if isinstance(node, ast.Call):
            fname = call_name(node.func)
            if fname == "len":  # len(x) == x.shape[0]: static
                return False
            return any(self.expr_traced(a) for a in node.args) or any(
                self.expr_traced(kw.value) for kw in node.keywords)
        if isinstance(node, ast.Name):
            return node.id in self.traced
        return any(self.expr_traced(c) for c in ast.iter_child_nodes(node))
