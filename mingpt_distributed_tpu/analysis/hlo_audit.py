"""graftaudit — static verification of lowered program families
(ISSUE 15 tentpole).

graftlint (``analysis/core.py``) checks the *Python* the repo authors;
this module checks the *programs XLA actually built* from it. The
incidents that cost hardware rounds all lived below the AST: sharding
specs that compare unequal after jit normalization (PR 12), GSPMD
quietly inserting collectives into a "single-device" hot path, and
donation falling back to copies that double HBM. Each of those is
visible in the lowered artifact — the post-optimization HLO text, the
executable's ``input_output_alias`` table, ``output_shardings`` and
``cost_analysis()`` — so each becomes a statically checkable contract.

The auditor never executes the model. :class:`AuditLedger` subclasses
``telemetry/attribution.py``'s :class:`ProgramLedger` and captures
artifacts through its ``observe_lowered`` hook, so the exact
``register_attrib`` seams the attribution report already uses (engine,
speculative decoder, trainer) enumerate the program families here too —
a family is auditable if and only if it is attributable, and a family
registered without an audit contract is itself a finding (no silent
audit gaps).

Four checks per (family, variant) artifact, against the plain-dict
contracts the owning subsystems declare (``DecodeEngine
.audit_contracts`` et al. — serving code never imports this module):

* **collectives** — every collective instruction in the optimized HLO
  (``all-gather`` / ``all-reduce`` / ``all-to-all`` /
  ``collective-permute`` / ``reduce-scatter``, async ``-start/-done``
  forms normalized) must be declared in the contract's
  ``allowed_collectives``; host transfers are never allowed; and no
  collective result may be as large as one KV pool buffer
  (``pool_leaf_elems``) — reducing a per-token activation over tp is
  the design, gathering the pool is the regression.
* **donation** — the executable's ``input_output_alias`` entry count
  must equal the contract's ``donated`` (or be >= ``donated_min``):
  "donation requested but copied" fails the audit instead of doubling
  HBM at 3am.
* **sharding** — every K/V leaf of ``output_shardings`` must equal the
  contract's ``kv_output_sharding`` (the runtime-normalized
  NamedSharding); the contract spec itself must carry no trailing
  ``None`` (the PR 12 gotcha, also linted at the AST level by GL011).
* **budget** — ``cost_analysis()`` flops / bytes-accessed must match
  the committed ``program_budgets.json`` *exactly* (they are properties
  of the program, not measurements — no tolerance, no timing noise).

Output mirrors graftlint's conventions: a versioned ``graftaudit/1``
JSON envelope (sorted keys — two runs against the same jaxlib are
byte-identical), a human rendering, exit 0 clean / 1 findings / 2
usage.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from mingpt_distributed_tpu.analysis.core import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
)
from mingpt_distributed_tpu.telemetry.attribution import ProgramLedger

__all__ = [
    "AUDIT_SCHEMA",
    "BUDGETS_SCHEMA",
    "AuditFinding",
    "AuditLedger",
    "ProgramArtifact",
    "audit_programs",
    "build_audit_report",
    "build_budget_section",
    "check_budgets",
    "collective_inventory",
    "donated_alias_count",
    "dump_audit_report",
    "render_audit_human",
    "validate_audit_report",
]

AUDIT_SCHEMA = "graftaudit/1"
BUDGETS_SCHEMA = "graftaudit-budgets/1"

#: collective op base names (async -start/-done forms normalize to these)
COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-broadcast",
    "collective-permute",
    "reduce-scatter",
)

#: ops that move data between host and device — never allowed in a
#: serving/training hot path, whatever the contract says
HOST_TRANSFER_OPS = ("infeed", "outfeed", "recv", "send")

# An HLO instruction *definition*: `  [ROOT] %name = <shape> opcode(...`
# — anchoring on the `= shape opcode(` triple so operand references
# inside a line (which repeat opcode-like names) never count.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\([^)]*\)|\S+)"        # result shape: tuple or single token
    r"\s+([a-z][\w\-]*)\("    # opcode
)

# Element counts inside a shape string: every `[d0,d1,...]` group
# (`f32[]` is a scalar: empty dims, one element).
_DIMS_RE = re.compile(r"[a-z]\d*\[([\d,]*)\]")

# One input_output_alias table entry: `{out_idx...}: (arg, {sub}, kind)`.
# The inner `{}` of the entry body is followed by `,`, not `:`, so it
# can never match.
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\(\d+")


# ---------------------------------------------------------------------
# lowered-artifact capture
# ---------------------------------------------------------------------


@dataclass
class ProgramArtifact:
    """Everything the audit needs from one compiled program family
    member, captured at registration time (the lowered/compiled objects
    themselves are not retained)."""

    family: str
    variant: str
    hlo_text: str
    output_shardings: Any
    flops: Optional[float]
    bytes_accessed: Optional[float]

    @property
    def key(self) -> str:
        return f"{self.family}:{self.variant}" if self.variant \
            else self.family


class AuditLedger(ProgramLedger):
    """A ProgramLedger that additionally captures the lowered artifacts
    of every ``register_aot`` — the ``observe_lowered`` hook is the only
    seam, so anything that knows how to ``register_attrib`` is auditable
    without touching its registration code."""

    def __init__(self, registry=None):
        super().__init__(registry=registry)
        self.artifacts: Dict[Tuple[str, str], ProgramArtifact] = {}

    def observe_lowered(self, family, variant, lowered, compiled):
        try:
            cost = compiled.cost_analysis()
        except Exception:
            cost = None
        from mingpt_distributed_tpu.telemetry.attribution import (
            _cost_to_flops_bytes,
        )

        flops, byts = _cost_to_flops_bytes(cost)
        self.artifacts[(family, variant)] = ProgramArtifact(
            family=family,
            variant=variant,
            hlo_text=compiled.as_text(),
            output_shardings=compiled.output_shardings,
            flops=flops,
            bytes_accessed=byts,
        )


# ---------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------


def _shape_elems(shape: str) -> int:
    """Max element count over the (possibly tuple) result shape — the
    size of the largest buffer the instruction materializes."""
    best = 1
    for dims in _DIMS_RE.findall(shape):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n)
    return best


def _base_op(op: str) -> str:
    for suffix in ("-start", "-done"):
        if op.endswith(suffix):
            return op[: -len(suffix)]
    return op


def collective_inventory(hlo_text: str) -> List[Dict[str, Any]]:
    """Every collective / host-transfer instruction definition in the
    HLO text: ``[{"op", "elems", "line"}, ...]`` with async forms
    normalized to their base op (so an ``all-gather-start`` audits as an
    ``all-gather``, counted once — the ``-done`` carries no shape of its
    own worth double-counting)."""
    out: List[Dict[str, Any]] = []
    for lineno, line in enumerate(hlo_text.splitlines(), start=1):
        m = _INSTR_RE.match(line)
        if not m:
            continue
        shape, op = m.group(1), m.group(2)
        base = _base_op(op)
        is_collective = base in COLLECTIVE_OPS and not op.endswith("-done")
        is_host = base in HOST_TRANSFER_OPS or "is_host_transfer=true" in line
        if not (is_collective or is_host):
            continue
        out.append({
            "op": base if is_collective else op,
            "host_transfer": bool(is_host),
            "elems": _shape_elems(shape),
            "line": lineno,
        })
    return out


def donated_alias_count(hlo_text: str) -> int:
    """Number of ``input_output_alias`` entries in the executable — one
    per donated leaf XLA actually aliased. 0 when the header is absent
    (nothing donated, or everything silently copied)."""
    idx = hlo_text.find("input_output_alias=")
    if idx < 0:
        return 0
    # the alias table lives on the (single-line) HloModule header
    segment = hlo_text[idx:hlo_text.find("\n", idx)]
    return len(_ALIAS_ENTRY_RE.findall(segment))


def _kv_output_shardings(output_shardings: Any) -> List[Tuple[str, Any]]:
    """(path, sharding) for every K/V cache leaf of a program's output
    pytree — the leaves reached through a dict key ``"k"`` or ``"v"``
    (the ``Cache`` container every pool/prefix program returns), plus
    the ``k_scale``/``v_scale`` planes a quantized pool carries (their
    sharded axis is kv_heads too, so the same authored sharding must
    hold — a scale plane that gathered would silently replicate)."""
    import jax  # lazy: parsing-only callers never need a backend

    flat = jax.tree_util.tree_flatten_with_path(output_shardings)[0]
    out = []
    for path, shard in flat:
        keys = [p.key for p in path
                if isinstance(p, jax.tree_util.DictKey)]
        if any(k in ("k", "v", "k_scale", "v_scale") for k in keys):
            out.append((jax.tree_util.keystr(path), shard))
    return out


def _spec_has_trailing_none(sharding: Any) -> bool:
    spec = getattr(sharding, "spec", None)
    return bool(spec) and len(spec) > 0 and spec[-1] is None


# ---------------------------------------------------------------------
# findings + checks
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class AuditFinding:
    """One contract violation in one lowered program."""

    family: str
    variant: str
    check: str      # contract | collectives | donation | sharding | budget
    message: str

    @property
    def sort_key(self):
        return (self.family, self.variant, self.check, self.message)

    def to_dict(self) -> Dict[str, str]:
        return {
            "family": self.family,
            "variant": self.variant,
            "check": self.check,
            "message": self.message,
        }

    def render(self) -> str:
        where = f"{self.family}:{self.variant}" if self.variant \
            else self.family
        return f"{where} [{self.check}] {self.message}"


def _audit_one(art: ProgramArtifact, contract: Dict[str, Any],
               ) -> List[AuditFinding]:
    f: List[AuditFinding] = []

    def finding(check: str, message: str) -> None:
        f.append(AuditFinding(art.family, art.variant, check, message))

    # (a) collectives inventory
    allowed = set(contract.get("allowed_collectives", ()))
    pool_elems = contract.get("pool_leaf_elems")
    for item in collective_inventory(art.hlo_text):
        if item["host_transfer"]:
            finding("collectives",
                    f"host transfer {item['op']!r} at HLO line "
                    f"{item['line']} — never allowed in a compiled "
                    f"hot path")
            continue
        if item["op"] not in allowed:
            finding("collectives",
                    f"undeclared collective {item['op']!r} at HLO line "
                    f"{item['line']} (allowed: "
                    f"{sorted(allowed) or 'none'})")
        elif pool_elems is not None and item["elems"] >= pool_elems:
            finding("collectives",
                    f"{item['op']!r} at HLO line {item['line']} moves "
                    f"{item['elems']} elements — at least one whole KV "
                    f"pool buffer ({pool_elems}); collectives may touch "
                    f"activations, never the pool")

    # (b) donation verification
    got = donated_alias_count(art.hlo_text)
    want = contract.get("donated")
    want_min = contract.get("donated_min")
    if want is not None and got != want:
        finding("donation",
                f"executable aliases {got} input-output pairs, contract "
                f"requires exactly {want} — donation "
                + ("silently fell back to copies" if got < want
                   else "aliases more than the contract declares"))
    elif want_min is not None and got < want_min:
        finding("donation",
                f"executable aliases {got} input-output pairs, contract "
                f"requires at least {want_min} — donation silently fell "
                f"back to copies")

    # (c) sharding-spec drift
    if "kv_output_sharding" in contract:
        expected = contract["kv_output_sharding"]
        if expected is not None and _spec_has_trailing_none(expected):
            finding("sharding",
                    f"contract sharding spec {expected.spec} has a "
                    f"trailing None — not the runtime-normalized form "
                    f"(PR 12: equality-keyed executables would see a "
                    f"novel layout)")
        for path, shard in _kv_output_shardings(art.output_shardings):
            if expected is None:
                n_dev = len(getattr(shard, "device_set", ())) or 1
                if n_dev > 1:
                    finding("sharding",
                            f"output {path} is partitioned over {n_dev} "
                            f"devices on a single-device engine")
            elif shard != expected:
                finding("sharding",
                        f"output {path} sharding {shard} != authored "
                        f"normalized sharding {expected}")

    return f


def audit_programs(
    artifacts: Dict[Tuple[str, str], ProgramArtifact],
    contracts: Dict[str, Dict[str, Any]],
) -> List[AuditFinding]:
    """Run checks (a)-(c) for every captured artifact against its
    family's contract. A family with no contract is a finding (check
    ``contract``): audit coverage is part of the suite, so a new program
    family cannot land unaudited."""
    findings: List[AuditFinding] = []
    for (family, variant) in sorted(artifacts):
        art = artifacts[(family, variant)]
        contract = contracts.get(family)
        if contract is None:
            findings.append(AuditFinding(
                family, variant, "contract",
                f"program family {family!r} is registered in the "
                f"attribution ledger but declares no audit contract — "
                f"add one next to its jit definition"))
            continue
        findings.extend(_audit_one(art, contract))
    return sorted(findings, key=lambda x: x.sort_key)


# ---------------------------------------------------------------------
# cost budgets (check d)
# ---------------------------------------------------------------------


def build_budget_section(
    artifacts: Dict[Tuple[str, str], ProgramArtifact],
) -> Dict[str, Dict[str, Optional[float]]]:
    """The committed-budget entries for one sweep: exact
    ``cost_analysis`` numbers per program key (``family`` or
    ``family:variant``)."""
    out: Dict[str, Dict[str, Optional[float]]] = {}
    for (_, _), art in sorted(artifacts.items()):
        out[art.key] = {
            "flops": art.flops,
            "bytes_accessed": art.bytes_accessed,
        }
    return out


def check_budgets(
    artifacts: Dict[Tuple[str, str], ProgramArtifact],
    budgets: Optional[Dict[str, Dict[str, Optional[float]]]],
) -> List[AuditFinding]:
    """Exact-match comparison against one sweep's committed budgets.
    flops / bytes-accessed are properties of the compiled program, not
    measurements, so any drift is a real program change: bless it with
    ``tools/graftaudit.py --update-budgets`` or fix the regression."""
    findings: List[AuditFinding] = []
    if budgets is None:
        budgets = {}
    seen = set()
    for (family, variant) in sorted(artifacts):
        art = artifacts[(family, variant)]
        seen.add(art.key)
        want = budgets.get(art.key)
        if want is None:
            findings.append(AuditFinding(
                family, variant, "budget",
                f"no committed budget for {art.key!r} — run "
                f"tools/graftaudit.py --update-budgets and commit "
                f"program_budgets.json"))
            continue
        for metric, got in (("flops", art.flops),
                            ("bytes_accessed", art.bytes_accessed)):
            if got != want.get(metric):
                findings.append(AuditFinding(
                    family, variant, "budget",
                    f"{metric} = {got!r} != committed budget "
                    f"{want.get(metric)!r} (exact-match: bless "
                    f"intentional changes with --update-budgets)"))
    for key in sorted(set(budgets) - seen):
        findings.append(AuditFinding(
            key.split(":", 1)[0],
            key.split(":", 1)[1] if ":" in key else "",
            "budget",
            f"committed budget entry {key!r} matches no registered "
            f"program — stale entry, regenerate with --update-budgets"))
    return sorted(findings, key=lambda x: x.sort_key)


# ---------------------------------------------------------------------
# graftaudit/1 report
# ---------------------------------------------------------------------


def _contract_row(contract: Dict[str, Any]) -> Dict[str, Any]:
    row: Dict[str, Any] = {
        "allowed_collectives":
            sorted(contract.get("allowed_collectives", ())),
    }
    for k in ("donated", "donated_min", "pool_leaf_elems"):
        if k in contract:
            row[k] = contract[k]
    if "kv_output_sharding" in contract:
        sh = contract["kv_output_sharding"]
        row["kv_output_spec"] = None if sh is None else str(sh.spec)
    return row


def build_audit_report(
    sweep: Dict[str, Any],
    artifacts: Dict[Tuple[str, str], ProgramArtifact],
    contracts: Dict[str, Dict[str, Any]],
    findings: List[AuditFinding],
) -> Dict[str, Any]:
    """Assemble the versioned envelope. Everything in it is a property
    of the lowered programs (never a clock or a live-buffer readout), so
    two consecutive runs against the same jaxlib serialize
    byte-identically — the run_tests.sh gate ``cmp``s them."""
    programs = []
    for (family, variant) in sorted(artifacts):
        art = artifacts[(family, variant)]
        counts: Dict[str, int] = {}
        largest = 0
        for item in collective_inventory(art.hlo_text):
            counts[item["op"]] = counts.get(item["op"], 0) + 1
            largest = max(largest, item["elems"])
        programs.append({
            "family": family,
            "variant": variant,
            "collectives": dict(sorted(counts.items())),
            "largest_collective_elems": largest,
            "donated": donated_alias_count(art.hlo_text),
            "flops": art.flops,
            "bytes_accessed": art.bytes_accessed,
        })
    by_check: Dict[str, int] = {}
    for f in findings:
        by_check[f.check] = by_check.get(f.check, 0) + 1
    return {
        "schema": AUDIT_SCHEMA,
        "sweep": dict(sweep),
        "programs": programs,
        "contracts": {fam: _contract_row(c)
                      for fam, c in sorted(contracts.items())},
        "findings": [f.to_dict() for f in findings],
        "summary": {
            "programs": len(programs),
            "findings": len(findings),
            "by_check": dict(sorted(by_check.items())),
        },
    }


_PROGRAM_KEYS = ("family", "variant", "collectives",
                 "largest_collective_elems", "donated", "flops",
                 "bytes_accessed")
_FINDING_KEYS = ("family", "variant", "check", "message")


def validate_audit_report(report: Dict[str, Any]) -> None:
    """Strict structural validation (raises ValueError), mirroring
    ``validate_attrib_report`` so perf_diff/tests never defend."""
    if report.get("schema") != AUDIT_SCHEMA:
        raise ValueError(
            f"not a {AUDIT_SCHEMA} report: schema={report.get('schema')!r}")
    if not isinstance(report.get("sweep"), dict):
        raise ValueError("sweep must be an object")
    progs = report.get("programs")
    if not isinstance(progs, list):
        raise ValueError("programs must be a list")
    seen = set()
    for i, row in enumerate(progs):
        missing = set(_PROGRAM_KEYS) - set(row)
        if missing:
            raise ValueError(f"programs[{i}] missing {sorted(missing)}")
        key = (row["family"], row["variant"])
        if key in seen:
            raise ValueError(f"duplicate program row {key}")
        seen.add(key)
        if row["donated"] < 0 or row["largest_collective_elems"] < 0:
            raise ValueError(f"programs[{i}] has negative accounting")
    finds = report.get("findings")
    if not isinstance(finds, list):
        raise ValueError("findings must be a list")
    for i, row in enumerate(finds):
        missing = set(_FINDING_KEYS) - set(row)
        if missing:
            raise ValueError(f"findings[{i}] missing {sorted(missing)}")
    summary = report.get("summary")
    if not isinstance(summary, dict):
        raise ValueError("summary must be an object")
    if summary.get("programs") != len(progs):
        raise ValueError("summary.programs != len(programs)")
    if summary.get("findings") != len(finds):
        raise ValueError("summary.findings != len(findings)")


def dump_audit_report(report: Dict[str, Any]) -> str:
    """Canonical serialization: sorted keys, fixed separators — the
    byte-identity contract of the run_tests.sh double-run gate."""
    return json.dumps(report, sort_keys=True, indent=2)


def render_audit_human(report: Dict[str, Any]) -> str:
    sweep = report["sweep"]
    lines = [f"graftaudit ({report['schema']}): "
             f"{report['summary']['programs']} programs audited, "
             f"tp={sweep.get('tp')} over {sweep.get('devices')} device(s)"]
    lines.append(
        f"  {'family':<16} {'variant':<8} {'collectives':<28} "
        f"{'donated':>7} {'flops':>12} {'bytes':>12}")
    for row in report["programs"]:
        colls = ",".join(f"{op}x{n}"
                         for op, n in row["collectives"].items()) or "-"
        fl = "n/a" if row["flops"] is None else f"{row['flops']:.6g}"
        by = ("n/a" if row["bytes_accessed"] is None
              else f"{row['bytes_accessed']:.6g}")
        lines.append(
            f"  {row['family']:<16} {row['variant']:<8} {colls:<28} "
            f"{row['donated']:>7} {fl:>12} {by:>12}")
    if report["findings"]:
        lines.append(f"{report['summary']['findings']} finding(s):")
        for row in report["findings"]:
            where = (f"{row['family']}:{row['variant']}"
                     if row["variant"] else row["family"])
            lines.append(f"  {where} [{row['check']}] {row['message']}")
    else:
        lines.append("clean: every lowered program honours its contract")
    return "\n".join(lines)


def audit_exit_code(findings: List[AuditFinding]) -> int:
    return EXIT_FINDINGS if findings else EXIT_CLEAN
