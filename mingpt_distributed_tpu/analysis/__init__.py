"""graftlint — repo-specific JAX-aware static analysis (ISSUE 8 tentpole).

Three of this repo's worst bugs were invariant violations no generic
linter can see: donating externally-restored arrays into a
persistent-cache-deserialised executable (the PR 2 resume segfault),
silent recompiles that needed a *runtime* watchdog to catch (PR 4), and
the serving chaos harness only staying deterministic because serving
code never reads the wall clock directly (PR 5). Each of those
invariants was enforced by convention; ``graftlint`` enforces them at
review time, before a trace ever runs.

The engine is plain ``ast`` — no imports of the analysed code, so a
lint run can never initialise a JAX backend or dial TPU hardware — with
a rule registry (stable ``GLxxx`` IDs), inline
``# graftlint: disable=GLxxx`` suppressions, a checked-in baseline for
grandfathered findings, human and ``--json`` output, and deterministic
exit codes (0 clean, 1 findings, 2 usage error).

Usage::

    python -m mingpt_distributed_tpu.analysis mingpt_distributed_tpu tools *.py
    python -m mingpt_distributed_tpu.analysis --json --baseline lint_baseline.json
    python -m mingpt_distributed_tpu.analysis --list-rules

Rule catalog: ``docs/static_analysis.md``.
"""

from mingpt_distributed_tpu.analysis.core import (
    Config,
    Finding,
    Rule,
    all_rules,
    get_rule,
    register_rule,
)
from mingpt_distributed_tpu.analysis.engine import Engine, RunResult

__all__ = [
    "Config",
    "Engine",
    "Finding",
    "Rule",
    "RunResult",
    "all_rules",
    "get_rule",
    "register_rule",
]
