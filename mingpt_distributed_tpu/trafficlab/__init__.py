"""Traffic lab: open-loop load generation, multi-tenant workload mixes,
and pluggable admission policies graded on the same arrival trace.

ROADMAP's serving question — "at what offered load does TTFT/ITL p99
fall off a cliff, and which admission policy holds the SLO longest?" —
needs an *open-loop* generator: closed-loop selftests (submit, wait,
submit) self-throttle and can never expose queueing collapse, while
open-loop arrivals keep offering load whether or not the fleet keeps
up. Everything here runs on the serving VirtualClock: arrival schedules
are virtual-timestamp *data* sampled once from ``(seed, spec)``, so a
2-policy multi-rung sweep takes zero wall-clock reads (graftlint GL007
pins this), finishes in seconds, and is byte-identically replayable.

* ``arrivals.py`` — seeded arrival processes (Poisson, bursty on/off,
  ramp) emitting absolute virtual timestamps via Lewis–Shedler
  thinning, plus ``recorded:`` literal replay of imported traces
  (control/importer.py emits these from mingpt-trace/1 logs).
* ``workloads.py`` — multi-tenant mixes (chat / completion /
  long-context / shared-prefix families) rendered into concrete
  ``Request``s; shared-prefix pools exercise the PrefixKVStore.
* ``policies.py`` — deadline-aware EDF and fair-share per-tenant
  ``AdmissionPolicy`` implementations plus the name registry (FIFO
  itself lives in serving/admission.py as the extracted default).
* ``runner.py`` / ``report.py`` — the load-sweep driver (ladder of
  offered-load rungs, each policy replayed on the identical trace,
  ServingFaultInjector as an optional chaos axis) and the versioned
  ``mingpt-traffic/1`` report with SLO grades and knee location.

CLI: ``traffic.py`` at the repo root; ``bench.py --traffic`` embeds the
sweep summary in the BENCH record; ``run_tests.sh --selftest-traffic``
gates it.
"""

from mingpt_distributed_tpu.trafficlab.arrivals import (
    BurstySpec,
    PoissonSpec,
    RampSpec,
    RecordedSpec,
    arrival_times,
    format_arrival_spec,
    parse_arrival_spec,
)
from mingpt_distributed_tpu.trafficlab.policies import (
    POLICIES,
    DeadlinePolicy,
    FairSharePolicy,
    make_policy,
)
from mingpt_distributed_tpu.trafficlab.report import (
    TRAFFIC_SCHEMA,
    locate_knees,
    render_traffic_report,
    validate_traffic_report,
)
from mingpt_distributed_tpu.trafficlab.runner import SweepSpec, run_sweep
from mingpt_distributed_tpu.trafficlab.workloads import (
    TenantSpec,
    TimedRequest,
    WorkloadMix,
    default_mix,
)

__all__ = [
    "BurstySpec",
    "DeadlinePolicy",
    "FairSharePolicy",
    "POLICIES",
    "PoissonSpec",
    "RampSpec",
    "RecordedSpec",
    "SweepSpec",
    "TRAFFIC_SCHEMA",
    "TenantSpec",
    "TimedRequest",
    "WorkloadMix",
    "arrival_times",
    "default_mix",
    "format_arrival_spec",
    "locate_knees",
    "make_policy",
    "parse_arrival_spec",
    "render_traffic_report",
    "run_sweep",
    "validate_traffic_report",
]
