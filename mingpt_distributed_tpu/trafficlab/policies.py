"""Admission policies beyond FIFO, plus the name registry.

The interface (:class:`AdmissionPolicy`) and the behavior-preserving
FIFO default live in ``serving/admission.py`` — the serving package
must not import trafficlab. This module holds the policies the traffic
lab actually compares, keyed by name for CLI/report use:

* ``edf`` — earliest-deadline-first: deadline-carrying requests admit
  in deadline order ahead of deadline-free ones. Under overload this
  trades batch-job latency for chat deadline hit-rate, which is exactly
  the separation the sweep report grades.
* ``fair`` — fair-share per tenant: the tenant with the fewest
  admissions so far goes first, so one bursty tenant cannot starve the
  rest of the mix. Stateful: the scheduler's ``on_admit`` maintains the
  counts, and because the fleet router deliberately does NOT call
  ``on_admit`` (serving/admission.py), sharing one policy object across
  router + replicas counts each admission exactly once.
* ``health`` — lives in ``serving/admission.py`` (it is a serving-side
  policy, registered here for grading): FIFO while the fleet is
  healthy, EDF once any routable replica fails a health gate.

Every ``sort_key`` ends in the queue position, so equal-priority
requests keep FIFO order and the whole schedule stays deterministic on
the VirtualClock.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from mingpt_distributed_tpu.serving.admission import (
    AdmissionPolicy,
    FifoPolicy,
    HealthAwarePolicy,
)

__all__ = [
    "POLICIES",
    "DeadlinePolicy",
    "FairSharePolicy",
    "make_policy",
]


class DeadlinePolicy(AdmissionPolicy):
    """Earliest-deadline-first. Handles expose ``.deadline`` (absolute
    clock seconds or None) on both the scheduler and router queues;
    deadline-free handles sort after every deadline-carrying one."""

    name = "edf"

    def sort_key(self, handle: Any, position: int, now: float) -> Tuple:
        deadline = getattr(handle, "deadline", None)
        if deadline is None:
            return (1, 0.0, position)
        return (0, float(deadline), position)


class FairSharePolicy(AdmissionPolicy):
    """Least-admitted tenant first. Tenant comes from
    ``handle.request.tenant`` (None buckets to ``"_"``); counts update
    in ``on_admit`` — i.e. when a request actually claims a KV slot."""

    name = "fair"

    def __init__(self) -> None:
        self.admitted: Dict[str, int] = {}

    def _tenant(self, handle: Any) -> str:
        request = getattr(handle, "request", None)
        tenant = getattr(request, "tenant", None)
        return tenant if tenant is not None else "_"

    def sort_key(self, handle: Any, position: int, now: float) -> Tuple:
        return (self.admitted.get(self._tenant(handle), 0), position)

    def on_admit(self, handle: Any) -> None:
        tenant = self._tenant(handle)
        self.admitted[tenant] = self.admitted.get(tenant, 0) + 1


#: registry for CLI flags and report keys. Values are FACTORIES —
#: stateful policies (fair) must be fresh per run, never shared across
#: sweep rungs.
POLICIES = {
    "fifo": FifoPolicy,
    "edf": DeadlinePolicy,
    "fair": FairSharePolicy,
    # FIFO while healthy, EDF once any routable replica degrades; the
    # runner binds the live signals seam per cell (ISSUE 20)
    "health": HealthAwarePolicy,
}


def make_policy(name: str) -> AdmissionPolicy:
    """Fresh policy instance by registry name."""
    try:
        factory = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown admission policy {name!r} (want one of "
            f"{sorted(POLICIES)})")
    return factory()
