"""The load-sweep driver: offered-load ladder x admission policies on
one arrival trace, all on VirtualClock.

``run_sweep`` steps a base arrival spec through a ladder of load
factors. Per rung it samples the arrival trace ONCE and renders it
ONCE; every policy then replays that identical trace against a fresh
fleet (fresh VirtualClock, TraceRecorder, supervisor, router, policy
instance — nothing leaks between cells, and ``TimedRequest.to_request``
mints fresh Request objects per policy so runs can't see each other's
mutations). The drive loop is open-loop: arrivals whose virtual
timestamp has come due are submitted whether or not the fleet kept up —
``ShedError`` becomes an outcome row, not an exception — then the
router steps (which ticks the clock), and idle gaps fast-forward the
clock to the next arrival instead of burning rounds.

Grading joins the rendered trace against the TraceRecorder's
per-request summaries by fleet request id (the router's own
``fleet-shed-*`` traces are deliberately NOT rows — the shed
submissions already are, so sheds would double-count) and hands the
rows to ``telemetry.slo.evaluate_slos``. A ``ServingFaultInjector``
spec composes as a chaos axis: the same sweep, graded under crashes.

Network chaos is a second axis (ISSUE 19): ``n_hosts > 1`` runs every
cell on the loopback cross-host mesh (``build_loopback_fleet`` — a
:class:`CrossHostRouter` over per-host ProcessSupervisors, all on the
cell's VirtualClock), and ``net_chaos_spec`` drives the shared
``NetworkFaultInjector`` (``partition`` / ``drop_frame`` /
``slow_link`` / ``host_kill``). Cross-host failover rows carry the
same per-request ``recovery_s`` scalar the thread-fleet rows do, so
the ``recovery_slo_s`` tail objective grades a partition's failovers
exactly like a crash's re-routes — and the whole sweep stays
byte-replayable, partitions included.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, List, Optional, Tuple

# control submodules are imported directly (never ``control/__init__``):
# the package facade pulls in the trace importer, which imports
# trafficlab.arrivals — going through it from here would be a cycle
from mingpt_distributed_tpu.control.controller import (
    SLOAutoscaler,
    parse_controller_spec,
)
from mingpt_distributed_tpu.control.cost import cost_from_cell
from mingpt_distributed_tpu.control.signals import FleetSignalsView
from mingpt_distributed_tpu.serving.fleet import (
    ReplicaSupervisor,
    Router,
    VirtualClock,
    default_server_factory,
)
from mingpt_distributed_tpu.serving.requests import ShedError
from mingpt_distributed_tpu.telemetry.slo import (
    DEFAULT_SLO_SPEC,
    evaluate_slos,
    parse_slo_spec,
)
from mingpt_distributed_tpu.telemetry.tracing import TraceRecorder
from mingpt_distributed_tpu.trafficlab.arrivals import (
    arrival_times,
    parse_arrival_spec,
    spec_to_json,
)
from mingpt_distributed_tpu.trafficlab.policies import make_policy
from mingpt_distributed_tpu.trafficlab.report import (
    TRAFFIC_SCHEMA,
    headline_knee,
    locate_knees,
    validate_traffic_report,
)
from mingpt_distributed_tpu.trafficlab.workloads import (
    TimedRequest,
    WorkloadMix,
    default_mix,
    trace_digest,
)
from mingpt_distributed_tpu.training.faults import (
    NetworkFaultInjector,
    ServingFaultInjector,
)

__all__ = [
    "SweepSpec",
    "run_sweep",
]


@dataclasses.dataclass(frozen=True)
class SweepSpec:
    """Everything a sweep needs besides model params and the mix —
    (seed, SweepSpec, mix) fully determines the report bytes."""

    arrival: str = "poisson:rate=60.0"
    ladder: Tuple[float, ...] = (1.0, 2.0, 4.0)
    policies: Tuple[str, ...] = ("fifo", "edf")
    n_requests: int = 64
    seed: int = 0
    n_replicas: int = 2
    n_slots: int = 4
    tick_s: float = 0.001
    slo: str = "default"
    knee_objective: Optional[str] = None  # None: first objective in spec
    chaos_spec: Optional[str] = None
    #: recovery-tail objective (ISSUE 17): when set, appends
    #: ``recovery_p99<=X`` to the SLO spec — p99 of per-request
    #: fault -> first-replacement-token time, so a chaos sweep grades
    #: how fast failover is, not just whether streams stay exact
    recovery_slo_s: Optional[float] = None
    shed_watermark: Optional[int] = None
    prefix_cache_mb: float = 0.0
    max_rounds: int = 200_000
    #: cross-host axis (ISSUE 19): > 1 runs every cell on the loopback
    #: host mesh (n_replicas becomes per-host), where network chaos and
    #: quorum sheds exist
    n_hosts: int = 1
    #: NetworkFaultInjector grammar (partition / drop_frame / slow_link
    #: / host_kill) — requires n_hosts > 1
    net_chaos_spec: Optional[str] = None
    heartbeat_interval_s: float = 0.05
    #: controller axis (ISSUE 20): each entry is ``"static"`` (no
    #: control loop — the historical behaviour) or an ``auto[:k=v...]``
    #: SLOAutoscaler spec. Every policy runs once per controller on the
    #: identical rung trace; autoscaled cells are labelled
    #: ``policy+auto`` in the report so static and controlled runs of
    #: the same policy grade side by side.
    controllers: Tuple[str, ...] = ("static",)

    def effective_slo(self) -> str:
        """The SLO spec with the recovery-tail objective folded in."""
        if self.recovery_slo_s is None:
            return self.slo
        base = (DEFAULT_SLO_SPEC if self.slo.strip() == "default"
                else self.slo)
        return f"{base},recovery_p99<={self.recovery_slo_s:g}"

    def validate(self) -> None:
        parse_arrival_spec(self.arrival)
        if len(self.ladder) < 1:
            raise ValueError("ladder needs at least one load factor")
        if any(b <= a for a, b in zip(self.ladder, self.ladder[1:])):
            raise ValueError(
                f"ladder must be strictly increasing, got {self.ladder}")
        if any(f <= 0 for f in self.ladder):
            raise ValueError("ladder factors must be > 0")
        if not self.policies or len(set(self.policies)) != len(self.policies):
            raise ValueError(f"bad policy list {self.policies}")
        for p in self.policies:
            make_policy(p)
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.recovery_slo_s is not None and self.recovery_slo_s <= 0:
            raise ValueError(
                f"recovery_slo_s must be > 0, got {self.recovery_slo_s}")
        if self.n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {self.n_hosts}")
        if self.net_chaos_spec:
            # validates the op vocabulary (partition/drop_frame/...)
            NetworkFaultInjector(self.net_chaos_spec)
            if self.n_hosts < 2:
                raise ValueError(
                    "net_chaos_spec needs a mesh: set n_hosts >= 2 "
                    "(network faults have no single-host fault point)")
        if self.n_hosts > 1:
            if self.chaos_spec:
                raise ValueError(
                    "chaos_spec (ServingFaultInjector) is the thread-"
                    "fleet axis; on a host mesh use net_chaos_spec")
            if self.shed_watermark is not None:
                raise ValueError(
                    "shed_watermark is a single-host Router knob; the "
                    "host mesh sheds on lost quorum instead")
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got "
                f"{self.heartbeat_interval_s}")
        if not self.controllers or (
                len(set(self.controllers)) != len(self.controllers)):
            raise ValueError(f"bad controller list {self.controllers}")
        for ctrl in self.controllers:
            if (parse_controller_spec(ctrl) is not None
                    and self.n_hosts > 1):
                raise ValueError(
                    "autoscaled cells actuate the thread fleet's "
                    "router/supervisor seams; on a host mesh use "
                    "controllers=('static',)")
        parse_slo_spec(self.effective_slo())


def _run_one_crosshost(params, cfg, spec: SweepSpec, policy_name: str,
                       timed: List[TimedRequest],
                       server_kwargs: Optional[Dict[str, Any]],
                       ) -> Dict[str, Any]:
    """One cross-host (rung, policy) cell: the identical open-loop
    drive, but against a fresh loopback host mesh under network chaos.
    Rows are built from the CrossHandles (TTFT from the handle's first
    caller-visible token, ITL from per-token clock stamps collected at
    the frontend's on_token hook — the fence means a token is stamped
    exactly once), so a failed-over request's ``recovery_s`` grades the
    recovery-tail objective just like a thread-fleet crash row."""
    from mingpt_distributed_tpu.serving.procfleet.hostplane import (
        build_loopback_fleet,
    )

    clock = VirtualClock(tick_s=spec.tick_s, start=0.0)
    policy = make_policy(policy_name)
    token_times: Dict[str, List[float]] = {}
    frontend, _agents, _net = build_loopback_fleet(
        params, cfg, n_hosts=spec.n_hosts, n_replicas=spec.n_replicas,
        clock=clock, net_faults=spec.net_chaos_spec or "",
        heartbeat_interval_s=spec.heartbeat_interval_s,
        server_kwargs=dict(n_slots=spec.n_slots,
                           prefix_cache_mb=spec.prefix_cache_mb,
                           admission_policy=policy,
                           **(server_kwargs or {})),
        on_token=lambda c, _t: token_times.setdefault(
            c.request_id, []).append(clock.now()))

    handles: Dict[str, Any] = {}
    shed: Dict[str, str] = {}
    i = 0
    rounds = 0
    in_flight = True
    while i < len(timed) or in_flight:
        now = clock.now()
        while i < len(timed) and timed[i].t <= now:
            tr = timed[i]
            try:
                handles[tr.request_id] = frontend.submit(tr.to_request())
            except ShedError as e:
                shed[tr.request_id] = e.reason
            i += 1
        in_flight = frontend.step()
        rounds += 1
        if not in_flight and i < len(timed) and timed[i].t > clock.now():
            clock.advance(timed[i].t - clock.now())
        if rounds > spec.max_rounds:
            raise RuntimeError(
                f"cross-host sweep cell not drained after "
                f"{spec.max_rounds} rounds (policy={policy_name}, "
                f"submitted={i}/{len(timed)})")

    rows: List[Dict[str, Any]] = []
    counts = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    tokens = 0
    deadline_total = deadline_hit = 0
    for tr in timed:
        if tr.request_id in shed:
            rows.append({"request_id": tr.request_id, "outcome": "shed",
                         "ttft_s": None, "itl_s": []})
            counts["shed"] += 1
            if tr.deadline_s is not None:
                deadline_total += 1
            continue
        cross = handles[tr.request_id]
        outcome = cross.finish_reason or "error"
        stamps = token_times.get(cross.request_id, [])
        row = {
            "request_id": cross.request_id,
            "outcome": outcome,
            "ttft_s": (None if cross.first_token_time is None
                       else cross.first_token_time - cross.submit_time),
            "itl_s": [b - a for a, b in zip(stamps, stamps[1:])],
        }
        if cross.recovery_s is not None:
            row["recovery_s"] = cross.recovery_s
        rows.append(row)
        if outcome in ("length", "eos"):
            counts["completed"] += 1
        elif outcome == "deadline":
            counts["expired"] += 1
        else:
            counts["errors"] += 1
        tokens += len(cross.tokens)
        if tr.deadline_s is not None:
            deadline_total += 1
            if outcome in ("length", "eos"):
                deadline_hit += 1
    cell = {
        "slo": evaluate_slos(rows, parse_slo_spec(spec.effective_slo())),
        "deadline_hit_rate": (
            (deadline_hit / deadline_total) if deadline_total else None),
        "deadline_requests": deadline_total,
        "recovered": sum(1 for row in rows
                         if row.get("recovery_s") is not None),
        "completed": counts["completed"],
        "shed": counts["shed"],
        "expired": counts["expired"],
        "errors": counts["errors"],
        "tokens": tokens,
        "rounds": rounds,
        "virtual_duration_s": clock.now(),
    }
    cell["cost"] = cost_from_cell(cell)
    return cell


def _run_one(params, cfg, spec: SweepSpec, policy_name: str,
             timed: List[TimedRequest],
             server_kwargs: Optional[Dict[str, Any]],
             controller_spec: Optional[str] = None,
             control_sink: Optional[Callable[[str], None]] = None,
             ) -> Dict[str, Any]:
    """One (rung, policy, controller) cell: fresh fleet, replayed
    trace, SLO rows — plus, when ``controller_spec`` is an ``auto:``
    spec, an :class:`SLOAutoscaler` attached to the router (the control
    tick rides ``router.step()``, so the whole closed loop replays
    byte-identically on the cell's VirtualClock)."""
    if spec.n_hosts > 1:
        return _run_one_crosshost(params, cfg, spec, policy_name, timed,
                                  server_kwargs)
    clock = VirtualClock(tick_s=spec.tick_s, start=0.0)
    # sheds are recorded as extra traces, so size the ring for both
    recorder = TraceRecorder(max_completed=2 * len(timed) + 64)
    policy = make_policy(policy_name)
    injector = (ServingFaultInjector(spec.chaos_spec)
                if spec.chaos_spec else None)
    factory = default_server_factory(
        params, cfg, n_slots=spec.n_slots,
        prefix_cache_mb=spec.prefix_cache_mb,
        admission_policy=policy, **(server_kwargs or {}))
    supervisor = ReplicaSupervisor(
        factory, n_replicas=spec.n_replicas, clock=clock,
        injector=injector)
    router = Router(
        supervisor, trace_recorder=recorder, admission_policy=policy,
        shed_watermark=spec.shed_watermark)
    if hasattr(policy, "bind"):
        # health-aware admission reads live fleet state through the
        # signals seam; binding after the router exists closes the loop
        policy.bind(FleetSignalsView(router))
    controller = None
    if controller_spec is not None:
        ccfg = parse_controller_spec(controller_spec)
        if ccfg is not None:
            controller = SLOAutoscaler(router, ccfg)
            router.controller = controller

    handles: Dict[str, Any] = {}
    shed: Dict[str, str] = {}
    i = 0
    rounds = 0
    in_flight = True
    while i < len(timed) or in_flight:
        now = clock.now()
        while i < len(timed) and timed[i].t <= now:
            tr = timed[i]
            try:
                handles[tr.request_id] = router.submit(tr.to_request())
            except ShedError as e:
                shed[tr.request_id] = e.reason
            i += 1
        in_flight = router.step()
        rounds += 1
        if not in_flight and i < len(timed) and timed[i].t > clock.now():
            # fleet idle until the next arrival: fast-forward instead of
            # spinning one tick at a time
            clock.advance(timed[i].t - clock.now())
        if rounds > spec.max_rounds:
            raise RuntimeError(
                f"sweep cell not drained after {spec.max_rounds} rounds "
                f"(policy={policy_name}, submitted={i}/{len(timed)})")

    summaries = {s["request_id"]: s
                 for s in recorder.completed_requests()}
    rows: List[Dict[str, Any]] = []
    counts = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    tokens = 0
    deadline_total = deadline_hit = 0
    for tr in timed:
        if tr.request_id in shed:
            rows.append({"request_id": tr.request_id, "outcome": "shed",
                         "ttft_s": None, "itl_s": []})
            counts["shed"] += 1
            if tr.deadline_s is not None:
                deadline_total += 1
            continue
        fh = handles[tr.request_id]
        summary = summaries.get(fh.request_id)
        if summary is None:  # pragma: no cover - recorder ring overflow
            summary = {"request_id": fh.request_id,
                       "outcome": fh.finish_reason or "error",
                       "ttft_s": None, "itl_s": []}
        rows.append(summary)
        outcome = summary["outcome"]
        if outcome in ("length", "eos"):
            counts["completed"] += 1
        elif outcome == "deadline":
            counts["expired"] += 1
        else:
            counts["errors"] += 1
        tokens += len(fh.tokens)
        if tr.deadline_s is not None:
            deadline_total += 1
            if outcome in ("length", "eos"):
                deadline_hit += 1
    cell = {
        "slo": evaluate_slos(rows, parse_slo_spec(spec.effective_slo())),
        "deadline_hit_rate": (
            (deadline_hit / deadline_total) if deadline_total else None),
        "deadline_requests": deadline_total,
        # requests a crash re-routed (their summaries carry recovery_s:
        # fault observed -> first token from the replacement replica)
        "recovered": sum(1 for row in rows
                         if row.get("recovery_s") is not None),
        "completed": counts["completed"],
        "shed": counts["shed"],
        "expired": counts["expired"],
        "errors": counts["errors"],
        "tokens": tokens,
        "rounds": rounds,
        "virtual_duration_s": clock.now(),
    }
    cell["cost"] = cost_from_cell(cell)
    if controller is not None:
        log_text = controller.render_log()
        cell["control"] = {
            "spec": controller_spec,
            "ticks": controller.tick,
            "actions": controller.action_counts(),
            "final_replicas": sum(
                1 for rep in supervisor.replicas
                if rep.state != "drained" and not rep.draining),
            "log_sha256": hashlib.sha256(
                log_text.encode("utf-8")).hexdigest(),
        }
        if control_sink is not None:
            control_sink(log_text)
    return cell


def _cell_plan(spec: SweepSpec) -> List[Tuple[str, str, Optional[str]]]:
    """``(label, policy, controller_spec_or_None)`` per cell,
    policy-major. "static" keeps the bare policy name so
    single-controller reports are shaped exactly as before ISSUE 20;
    auto controllers suffix ``+auto`` (indexed when several)."""
    auto_specs = [c for c in spec.controllers
                  if parse_controller_spec(c) is not None]
    plan: List[Tuple[str, str, Optional[str]]] = []
    for policy in spec.policies:
        for ctrl in spec.controllers:
            if parse_controller_spec(ctrl) is None:
                plan.append((policy, policy, None))
            else:
                suffix = ("auto" if len(auto_specs) == 1
                          else f"auto{auto_specs.index(ctrl)}")
                plan.append((f"{policy}+{suffix}", policy, ctrl))
    return plan


def run_sweep(params, cfg, spec: SweepSpec,
              mix: Optional[WorkloadMix] = None,
              server_kwargs: Optional[Dict[str, Any]] = None,
              control_log_sink: Optional[
                  Callable[[int, str, str], None]] = None,
              ) -> Dict[str, Any]:
    """Run the full ladder x policy x controller grid; returns a
    validated mingpt-traffic/1 report dict (see report.py for the
    shape). ``control_log_sink(rung_index, cell_label, log_text)``
    receives each autoscaled cell's full mingpt-control/1 document —
    the report itself carries only its sha256."""
    spec.validate()
    if mix is None:
        mix = default_mix(vocab_size=cfg.vocab_size,
                          block_size=cfg.block_size)
    mix.validate()
    base = parse_arrival_spec(spec.arrival)
    objectives = parse_slo_spec(spec.effective_slo())
    knee_objective = (spec.knee_objective if spec.knee_objective
                      else objectives[0].name)
    if knee_objective not in {o.name for o in objectives}:
        raise ValueError(
            f"knee objective {knee_objective!r} not in SLO spec "
            f"{spec.slo!r}")
    plan = _cell_plan(spec)
    labels = [label for label, _, _ in plan]
    rungs: List[Dict[str, Any]] = []
    for rung_idx, factor in enumerate(spec.ladder):
        scaled = base.scaled(factor)
        times = arrival_times(scaled, spec.n_requests, spec.seed)
        # rendering draws from an RNG keyed by (seed, mix) only, so
        # every rung offers the SAME request bodies, just faster
        timed = mix.render(times, spec.seed)
        cells = {}
        for label, policy, ctrl in plan:
            sink = None
            if control_log_sink is not None and ctrl is not None:
                sink = (lambda text, r=rung_idx, lb=label:
                        control_log_sink(r, lb, text))
            cells[label] = _run_one(params, cfg, spec, policy, timed,
                                    server_kwargs, controller_spec=ctrl,
                                    control_sink=sink)
        rungs.append({
            "rung": rung_idx,
            "load_factor": float(factor),
            "offered_rate": float(scaled.mean_rate()),
            "n_requests": len(timed),
            "trace_sha256": trace_digest(timed),
            "policies": cells,
        })
    report: Dict[str, Any] = {
        "schema": TRAFFIC_SCHEMA,
        "seed": spec.seed,
        "arrival": spec_to_json(base),
        "mix": mix.to_json(),
        "slo_spec": spec.effective_slo(),
        "knee_objective": knee_objective,
        "chaos_spec": spec.chaos_spec,
        "net_chaos_spec": spec.net_chaos_spec,
        "fleet": {"n_replicas": spec.n_replicas, "n_slots": spec.n_slots,
                  "tick_s": spec.tick_s, "n_hosts": spec.n_hosts},
        "ladder": [float(f) for f in spec.ladder],
        "controllers": list(spec.controllers),
        # cell labels, not bare policy names: report consumers (knees,
        # validation, rendering) treat each (policy, controller) pair
        # as its own graded column
        "policies": labels,
        "rungs": rungs,
    }
    report["knees"] = locate_knees(rungs, labels)
    report["knee"] = headline_knee(report)
    validate_traffic_report(report, strict=True)
    return report
