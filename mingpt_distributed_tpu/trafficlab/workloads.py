"""Multi-tenant workload mixes rendered into concrete Requests.

A :class:`WorkloadMix` is a weighted set of :class:`TenantSpec`s —
request *families* (chat, completion, long-context, shared-prefix) with
per-tenant prompt-length and max-token distributions, deadlines, and
optional shared-prefix pools that exercise the PrefixKVStore (many
requests opening with the same system-prompt tokens, so replica-level
prefix reuse and the router's prefix affinity both engage).

``render(arrivals, seed, ...)`` marries an arrival-time list from
``arrivals.py`` to sampled request bodies, producing a list of
:class:`TimedRequest` — plain data, fully determined by
``(seed, mix, arrival trace)``. ``TimedRequest.to_request()`` mints a
FRESH ``Request`` object on every call: the sweep runner replays the
same rendered trace once per policy, and handing each run its own
Request objects keeps them from seeing each other's mutations (the
router stamps ``trace`` onto the Request it routes).

Token ids are synthetic (uniform over the vocab) — serving latency on
the tiny CPU config does not depend on token *values*, only lengths,
and synthetic ids keep the lab free of tokenizer dependencies.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from mingpt_distributed_tpu.serving.requests import Request
from mingpt_distributed_tpu.trafficlab.arrivals import _stream_seed

__all__ = [
    "TenantSpec",
    "TimedRequest",
    "WorkloadMix",
    "default_mix",
    "trace_digest",
]

_FAMILIES = ("chat", "completion", "longctx", "prefix")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's request family.

    ``prompt_len`` / ``max_new`` are inclusive uniform-integer ranges.
    ``deadline_s`` is the per-request relative deadline (None = no
    deadline; the fleet then never sheds or expires it). A positive
    ``prefix_pool`` gives the tenant that many distinct shared prefixes
    of ``prefix_len`` tokens; each request opens with one of them, so
    ``prefix_pool=1`` is a single hot system prompt."""

    name: str
    family: str = "completion"
    weight: float = 1.0
    prompt_len: Tuple[int, int] = (4, 8)
    max_new: Tuple[int, int] = (4, 8)
    deadline_s: Optional[float] = None
    prefix_pool: int = 0
    prefix_len: int = 0

    def validate(self) -> None:
        if self.family not in _FAMILIES:
            raise ValueError(f"unknown family {self.family!r} "
                             f"(want one of {_FAMILIES})")
        if self.weight <= 0.0:
            raise ValueError(f"tenant {self.name!r} weight must be > 0")
        for label, (lo, hi) in (("prompt_len", self.prompt_len),
                                ("max_new", self.max_new)):
            if lo < 1 or hi < lo:
                raise ValueError(
                    f"tenant {self.name!r} {label} range ({lo}, {hi}) "
                    "must satisfy 1 <= lo <= hi")
        if self.prefix_pool < 0 or self.prefix_len < 0:
            raise ValueError("prefix_pool/prefix_len must be >= 0")
        if (self.prefix_pool > 0) != (self.prefix_len > 0):
            raise ValueError("prefix_pool and prefix_len go together")
        if self.prefix_len >= self.prompt_len[0]:
            if self.prefix_len > 0:
                raise ValueError(
                    f"tenant {self.name!r} prefix_len {self.prefix_len} "
                    f"must be < min prompt_len {self.prompt_len[0]} so "
                    "every prompt has a unique suffix")

    def to_json(self) -> Dict[str, object]:
        out = dataclasses.asdict(self)
        out["prompt_len"] = list(self.prompt_len)
        out["max_new"] = list(self.max_new)
        return out


@dataclasses.dataclass(frozen=True)
class WorkloadMix:
    """A weighted multi-tenant mix plus the vocab the synthetic token
    ids draw from."""

    tenants: Tuple[TenantSpec, ...]
    vocab_size: int = 96

    def validate(self) -> None:
        if not self.tenants:
            raise ValueError("workload mix needs at least one tenant")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in mix: {names}")
        if self.vocab_size < 4:
            raise ValueError("vocab_size must be >= 4")
        for t in self.tenants:
            t.validate()

    def canonical(self) -> str:
        """Stable string form — part of the RNG stream key."""
        return json.dumps(
            {"vocab_size": self.vocab_size,
             "tenants": [t.to_json() for t in self.tenants]},
            sort_keys=True, separators=(",", ":"))

    def to_json(self) -> Dict[str, object]:
        return {"vocab_size": self.vocab_size,
                "tenants": [t.to_json() for t in self.tenants]}

    def render(self, arrivals: Sequence[float],
               seed: int) -> List["TimedRequest"]:
        """Attach a sampled request body to each arrival timestamp."""
        self.validate()
        rng = np.random.RandomState(_stream_seed(seed, self.canonical()))
        weights = np.asarray([t.weight for t in self.tenants], dtype=float)
        weights = weights / weights.sum()
        # pre-draw each tenant's shared-prefix pool so pool contents
        # don't depend on which requests happened to arrive first
        pools: Dict[str, List[Tuple[int, ...]]] = {}
        for t in self.tenants:
            if t.prefix_pool > 0:
                pools[t.name] = [
                    tuple(int(x) for x in rng.randint(
                        1, self.vocab_size, size=t.prefix_len))
                    for _ in range(t.prefix_pool)
                ]
        out: List[TimedRequest] = []
        for i, ts in enumerate(arrivals):
            t = self.tenants[int(rng.choice(len(self.tenants), p=weights))]
            n_prompt = int(rng.randint(t.prompt_len[0], t.prompt_len[1] + 1))
            n_new = int(rng.randint(t.max_new[0], t.max_new[1] + 1))
            if t.prefix_pool > 0:
                prefix = pools[t.name][int(rng.randint(0, t.prefix_pool))]
                suffix_len = max(1, n_prompt - len(prefix))
                body = tuple(int(x) for x in rng.randint(
                    1, self.vocab_size, size=suffix_len))
                prompt = prefix + body
            else:
                prompt = tuple(int(x) for x in rng.randint(
                    1, self.vocab_size, size=n_prompt))
            out.append(TimedRequest(
                t=float(ts),
                tenant=t.name,
                prompt=prompt,
                max_new_tokens=n_new,
                deadline_s=t.deadline_s,
                request_id=f"tr{i:05d}-{t.name}",
            ))
        return out


@dataclasses.dataclass(frozen=True)
class TimedRequest:
    """One rendered arrival: WHEN (absolute virtual seconds) and WHAT."""

    t: float
    tenant: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    deadline_s: Optional[float]
    request_id: str

    def to_request(self) -> Request:
        """Mint a fresh Request (greedy decode: policy comparisons grade
        scheduling, not sampling). Fresh per call — see module docstring."""
        return Request(
            prompt=list(self.prompt),
            max_new_tokens=self.max_new_tokens,
            do_sample=False,
            deadline_s=self.deadline_s,
            request_id=self.request_id,
            tenant=self.tenant,
        )

    def to_json(self) -> Dict[str, object]:
        return {
            "t": self.t,
            "tenant": self.tenant,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "deadline_s": self.deadline_s,
            "request_id": self.request_id,
        }


def trace_digest(timed: Sequence[TimedRequest]) -> str:
    """sha256 over the canonical rendered trace — the report embeds it so
    "both policies saw the identical arrival trace" is checkable."""
    blob = json.dumps([tr.to_json() for tr in timed],
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def default_mix(vocab_size: int = 96, block_size: int = 48) -> WorkloadMix:
    """The stock four-tenant mix, scaled to fit ``block_size`` (prompt +
    max_new - 1 must stay inside the decode window so strict validation
    passes on the tiny selftest config).

    * ``chat`` — short prompts, tight deadline: the tenant EDF saves.
    * ``batch`` — completion jobs, no deadline: the tenant that clogs
      FIFO queues ahead of chat under overload.
    * ``longctx`` — long prompts exercising chunked prefill.
    * ``assist`` — shared-prefix family over a small pool of system
      prompts, exercising the PrefixKVStore + router prefix affinity.
    """
    # proportions of the block budget; floors keep tiny configs sane
    long_prompt = max(6, (block_size * 2) // 3)
    mid_prompt = max(4, block_size // 4)
    short_new = max(2, block_size // 12)
    mid_new = max(3, block_size // 8)
    prefix_len = max(2, block_size // 8)
    return WorkloadMix(
        vocab_size=vocab_size,
        tenants=(
            TenantSpec(name="chat", family="chat", weight=4.0,
                       prompt_len=(3, mid_prompt),
                       max_new=(2, short_new), deadline_s=0.8),
            TenantSpec(name="batch", family="completion", weight=3.0,
                       prompt_len=(4, mid_prompt),
                       max_new=(mid_new, 2 * mid_new)),
            TenantSpec(name="longctx", family="longctx", weight=1.0,
                       prompt_len=(mid_prompt, long_prompt),
                       max_new=(2, short_new)),
            TenantSpec(name="assist", family="prefix", weight=2.0,
                       prompt_len=(prefix_len + 2, mid_prompt + prefix_len),
                       max_new=(2, mid_new), deadline_s=1.5,
                       prefix_pool=3, prefix_len=prefix_len),
        ),
    )
