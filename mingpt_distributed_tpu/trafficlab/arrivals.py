"""Seeded open-loop arrival processes on virtual time.

An arrival spec is a small frozen dataclass describing an intensity
function lambda(t) in requests per *virtual* second. ``arrival_times``
samples n absolute timestamps from it with Lewis–Shedler thinning: draw
candidate gaps from a homogeneous Poisson process at the peak rate,
keep each candidate with probability lambda(t)/peak. The RNG stream is
derived from ``(seed, canonical spec string)``, so the same pair always
reproduces the same trace byte-for-byte — reports are replayable and
two policies can be graded on the *identical* arrival sequence.

Specs never read a clock: timestamps are data, interpreted later by the
sweep runner against the serving ``VirtualClock``. The grammar mirrors
the fault-spec style used elsewhere in the repo::

    poisson:rate=50
    bursty:rate_on=200:rate_off=5:period=2.0:duty=0.25
    ramp:rate0=10:rate1=400:duration=20
    recorded:times=0.0;0.012;0.5;1.25

``scaled(f)`` multiplies every intensity by ``f`` — the sweep ladder is
"the same shape, offered harder".

``recorded:`` (ISSUE 20) is the replay kind the trace importer
(control/importer.py) emits: its times are not sampled at all —
``arrival_times`` returns them verbatim, so a sweep over a recorded
spec replays production-shaped load byte-identically. ``scaled(f)``
divides every timestamp by ``f`` (gap compression), which is the same
"shape preserved, offered harder" ladder semantics as the synthetic
kinds.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, List, Tuple, Union

import numpy as np

__all__ = [
    "ArrivalSpec",
    "BurstySpec",
    "PoissonSpec",
    "RampSpec",
    "RecordedSpec",
    "arrival_times",
    "format_arrival_spec",
    "parse_arrival_spec",
    "spec_to_json",
]


def _fmt(x: float) -> str:
    """Canonical scalar rendering (``repr`` of float: shortest round-trip
    form, so format/parse/format is a fixed point and seeds derived from
    the string are stable)."""
    return repr(float(x))


@dataclasses.dataclass(frozen=True)
class PoissonSpec:
    """Homogeneous Poisson arrivals at ``rate`` req/s."""

    rate: float

    kind = "poisson"

    def rate_at(self, t: float) -> float:
        return self.rate

    def peak_rate(self) -> float:
        return self.rate

    def mean_rate(self) -> float:
        return self.rate

    def scaled(self, factor: float) -> "PoissonSpec":
        return PoissonSpec(rate=self.rate * factor)

    def to_string(self) -> str:
        return f"poisson:rate={_fmt(self.rate)}"


@dataclasses.dataclass(frozen=True)
class BurstySpec:
    """On/off (interrupted Poisson) arrivals: each ``period`` seconds
    spends ``duty`` fraction at ``rate_on`` and the rest at ``rate_off``.
    Models bursty tenants that overwhelm a fleet sized for the mean."""

    rate_on: float
    rate_off: float
    period: float
    duty: float

    kind = "bursty"

    def rate_at(self, t: float) -> float:
        phase = (t % self.period) / self.period
        return self.rate_on if phase < self.duty else self.rate_off

    def peak_rate(self) -> float:
        return max(self.rate_on, self.rate_off)

    def mean_rate(self) -> float:
        return self.rate_on * self.duty + self.rate_off * (1.0 - self.duty)

    def scaled(self, factor: float) -> "BurstySpec":
        return dataclasses.replace(self, rate_on=self.rate_on * factor,
                                   rate_off=self.rate_off * factor)

    def to_string(self) -> str:
        return (f"bursty:rate_on={_fmt(self.rate_on)}"
                f":rate_off={_fmt(self.rate_off)}"
                f":period={_fmt(self.period)}:duty={_fmt(self.duty)}")


@dataclasses.dataclass(frozen=True)
class RampSpec:
    """Linear ramp from ``rate0`` to ``rate1`` over ``duration`` seconds,
    holding ``rate1`` afterwards — a within-trace load sweep."""

    rate0: float
    rate1: float
    duration: float

    kind = "ramp"

    def rate_at(self, t: float) -> float:
        if t >= self.duration:
            return self.rate1
        frac = t / self.duration
        return self.rate0 + (self.rate1 - self.rate0) * frac

    def peak_rate(self) -> float:
        return max(self.rate0, self.rate1)

    def mean_rate(self) -> float:
        return 0.5 * (self.rate0 + self.rate1)

    def scaled(self, factor: float) -> "RampSpec":
        return dataclasses.replace(self, rate0=self.rate0 * factor,
                                   rate1=self.rate1 * factor)

    def to_string(self) -> str:
        return (f"ramp:rate0={_fmt(self.rate0)}:rate1={_fmt(self.rate1)}"
                f":duration={_fmt(self.duration)}")


@dataclasses.dataclass(frozen=True)
class RecordedSpec:
    """Literal arrival times imported from a ``mingpt-trace/1`` log
    (control/importer.py). Nothing is sampled: ``arrival_times``
    returns these timestamps exactly (plus ``start``), so the seed is
    irrelevant and two renders are trivially identical."""

    times: Tuple[float, ...]

    kind = "recorded"

    def __post_init__(self):
        if not self.times:
            raise ValueError("recorded spec needs at least one time")
        prev = None
        for t in self.times:
            t = float(t)
            if t < 0.0:
                raise ValueError(f"recorded time {t} < 0")
            if prev is not None and t < prev:
                raise ValueError(
                    f"recorded times must be non-decreasing "
                    f"({t} after {prev})")
            prev = t

    def duration(self) -> float:
        return float(self.times[-1] - self.times[0])

    def rate_at(self, t: float) -> float:
        """Arrivals inside the 1-second window centred on ``t`` —
        descriptive only (generation never thins a recorded spec)."""
        return float(sum(1 for x in self.times if t - 0.5 <= x < t + 0.5))

    def peak_rate(self) -> float:
        """Busiest 1-second window (two-pointer sweep over the sorted
        times) — at least 1.0, so shared validation holds."""
        best, lo = 1, 0
        for hi in range(len(self.times)):
            while self.times[hi] - self.times[lo] > 1.0:
                lo += 1
            best = max(best, hi - lo + 1)
        return float(best)

    def mean_rate(self) -> float:
        dur = self.duration()
        if dur <= 0.0:
            return float(len(self.times))
        return (len(self.times) - 1) / dur

    def scaled(self, factor: float) -> "RecordedSpec":
        if factor <= 0.0:
            raise ValueError(f"scale factor must be > 0, got {factor}")
        return RecordedSpec(times=tuple(t / factor for t in self.times))

    def to_string(self) -> str:
        return "recorded:times=" + ";".join(_fmt(t) for t in self.times)


ArrivalSpec = Union[PoissonSpec, BurstySpec, RampSpec, RecordedSpec]

_SPEC_FIELDS = {
    "poisson": ("rate",),
    "bursty": ("rate_on", "rate_off", "period", "duty"),
    "ramp": ("rate0", "rate1", "duration"),
}
_SPEC_TYPES = {"poisson": PoissonSpec, "bursty": BurstySpec, "ramp": RampSpec}


def parse_arrival_spec(text: str) -> ArrivalSpec:
    """Parse ``kind:key=val:key=val`` into a spec, validating ranges."""
    parts = [p for p in text.strip().split(":") if p]
    if not parts:
        raise ValueError("empty arrival spec")
    kind = parts[0].strip().lower()
    if kind == "recorded":
        # different value grammar: one 'times' field holding a
        # semicolon-separated timestamp list (colons are field seps)
        if len(parts) != 2 or not parts[1].startswith("times="):
            raise ValueError(
                "recorded spec must be recorded:times=t0;t1;... "
                f"(got {text!r})")
        body = parts[1][len("times="):]
        try:
            times = tuple(float(v) for v in body.split(";") if v != "")
        except ValueError:
            raise ValueError(
                f"non-numeric timestamp in recorded spec {text!r}")
        return RecordedSpec(times=times)
    if kind not in _SPEC_FIELDS:
        raise ValueError(
            f"unknown arrival kind {kind!r} (want one of "
            f"{sorted(_SPEC_FIELDS)})")
    kwargs: Dict[str, float] = {}
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(f"malformed arrival field {part!r} "
                             "(want key=value)")
        key, _, val = part.partition("=")
        key = key.strip()
        if key not in _SPEC_FIELDS[kind]:
            raise ValueError(f"unknown field {key!r} for arrival kind "
                             f"{kind!r} (want {_SPEC_FIELDS[kind]})")
        if key in kwargs:
            raise ValueError(f"duplicate field {key!r} in arrival spec")
        try:
            kwargs[key] = float(val)
        except ValueError:
            raise ValueError(
                f"non-numeric value {val!r} for arrival field {key!r}")
    missing = [f for f in _SPEC_FIELDS[kind] if f not in kwargs]
    if missing:
        raise ValueError(f"arrival spec {kind!r} missing fields {missing}")
    spec = _SPEC_TYPES[kind](**kwargs)
    _validate(spec)
    return spec


def _validate(spec: ArrivalSpec) -> None:
    if spec.peak_rate() <= 0.0:
        raise ValueError("arrival spec needs a positive peak rate")
    if isinstance(spec, BurstySpec):
        if spec.period <= 0.0:
            raise ValueError("bursty period must be > 0")
        if not (0.0 < spec.duty <= 1.0):
            raise ValueError("bursty duty must be in (0, 1]")
        if spec.rate_on < 0.0 or spec.rate_off < 0.0:
            raise ValueError("bursty rates must be >= 0")
    elif isinstance(spec, RampSpec):
        if spec.duration <= 0.0:
            raise ValueError("ramp duration must be > 0")
        if spec.rate0 < 0.0 or spec.rate1 < 0.0:
            raise ValueError("ramp rates must be >= 0")
    elif spec.rate <= 0.0:
        raise ValueError("poisson rate must be > 0")


def format_arrival_spec(spec: ArrivalSpec) -> str:
    """Canonical string form — the replay key together with the seed."""
    return spec.to_string()


def spec_to_json(spec: ArrivalSpec) -> Dict[str, object]:
    """JSON-embeddable description for the mingpt-traffic/1 report."""
    out: Dict[str, object] = {"kind": spec.kind}
    if isinstance(spec, RecordedSpec):
        out["n"] = len(spec.times)
        out["duration"] = spec.duration()
    else:
        for field in _SPEC_FIELDS[spec.kind]:
            out[field] = float(getattr(spec, field))
    out["spec"] = spec.to_string()
    out["mean_rate"] = float(spec.mean_rate())
    out["peak_rate"] = float(spec.peak_rate())
    return out


def _stream_seed(seed: int, canonical: str) -> int:
    """Derive a 32-bit RNG seed from (user seed, canonical spec string)
    so distinct specs under one user seed get decorrelated streams while
    the same pair always replays the same trace."""
    return (seed * 1000003 + zlib.crc32(canonical.encode("utf-8"))) % (2**32)


def arrival_times(spec: ArrivalSpec, n: int, seed: int,
                  start: float = 0.0) -> List[float]:
    """Sample ``n`` absolute virtual timestamps from ``spec``.

    Lewis–Shedler thinning against the peak rate: exact for any bounded
    lambda(t), and O(n * peak/mean) draws. Deterministic in
    ``(seed, format_arrival_spec(spec), n, start)``.
    """
    if n <= 0:
        return []
    if isinstance(spec, RecordedSpec):
        # replay, never sample: the recorded gaps ARE the trace
        if n > len(spec.times):
            raise ValueError(
                f"recorded spec holds {len(spec.times)} arrivals, "
                f"{n} requested — size n_requests to the trace")
        return [float(start) + float(t) for t in spec.times[:n]]
    rng = np.random.RandomState(_stream_seed(seed, spec.to_string()))
    lam_max = spec.peak_rate()
    out: List[float] = []
    t = float(start)
    while len(out) < n:
        t += float(rng.exponential(1.0 / lam_max))
        if rng.uniform() * lam_max <= spec.rate_at(t - start):
            out.append(t)
    return out
