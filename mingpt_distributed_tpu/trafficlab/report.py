"""The versioned ``mingpt-traffic/1`` sweep report.

One report captures one load sweep: an arrival-spec shape offered at
each rung of a load ladder, every admission policy replayed on the
IDENTICAL rendered trace per rung (the report embeds the trace sha256
so that claim is checkable), each (rung, policy) cell graded by the
telemetry SLO engine, plus knee location — the first rung where a
named objective fails. Shape::

    {
      "schema": "mingpt-traffic/1",
      "seed": ..., "arrival": {...}, "mix": {...},
      "slo_spec": "...", "knee_objective": "...",
      "chaos_spec": null | "crash:nth=...",
      "fleet": {"n_replicas": N, "n_slots": S, "tick_s": ...},
      "ladder": [f0, f1, ...], "policies": ["fifo", "edf"],
      "rungs": [{"rung": i, "load_factor": f, "offered_rate": r,
                 "n_requests": n, "trace_sha256": "...",
                 "policies": {"fifo": {"slo": <mingpt-slo/1>,
                                       "deadline_hit_rate": ...,
                                       "deadline_requests": ...,
                                       "completed": ..., "shed": ...,
                                       "expired": ..., "errors": ...,
                                       "tokens": ..., "rounds": ...,
                                       "virtual_duration_s": ...}, ...}}],
      "knees": {"fifo": {"ttft_p99": rung-or-null, ...}, ...},
      "knee": {"policy": ..., "objective": ..., "rung": ...,
               "valid": bool} | null
    }

``dump_report`` serializes with sorted keys and no timestamps, so the
same ``(seed, spec)`` always produces a byte-identical file.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from mingpt_distributed_tpu.telemetry.slo import SLO_SCHEMA

__all__ = [
    "TRAFFIC_SCHEMA",
    "dump_report",
    "headline_knee",
    "locate_knees",
    "render_traffic_report",
    "validate_traffic_report",
]

TRAFFIC_SCHEMA = "mingpt-traffic/1"

_POLICY_CELL_KEYS = frozenset({
    "slo", "deadline_hit_rate", "deadline_requests", "recovered",
    "completed", "shed", "expired", "errors", "tokens", "rounds",
    "virtual_duration_s",
})


def locate_knees(rungs: Sequence[Dict[str, Any]],
                 policies: Sequence[str],
                 ) -> Dict[str, Dict[str, Optional[int]]]:
    """Per policy, per objective name: the first rung index where the
    objective FAILS (``pass`` is False), or None if it never does.
    Rungs where an objective has no data (``pass`` None) neither fail
    nor reset the search — they're skipped."""
    knees: Dict[str, Dict[str, Optional[int]]] = {}
    for policy in policies:
        per_obj: Dict[str, Optional[int]] = {}
        for rung in rungs:
            cell = rung["policies"][policy]
            for row in cell["slo"]["objectives"]:
                name = row["name"]
                per_obj.setdefault(name, None)
                if per_obj[name] is None and row["pass"] is False:
                    per_obj[name] = int(rung["rung"])
        knees[policy] = per_obj
    return knees


def headline_knee(report: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The single knee the CLI prints: ``knee_objective`` under the
    first listed policy. ``valid`` means the textbook shape — passing at
    rung N-1, failing at rung N (a sweep that fails at rung 0 locates
    no knee, it just proves every rung is overloaded)."""
    policies = report["policies"]
    objective = report["knee_objective"]
    if not policies:
        return None
    policy = policies[0]
    rung_idx = report["knees"].get(policy, {}).get(objective)
    if rung_idx is None:
        return None
    valid = False
    if rung_idx > 0:
        prev = report["rungs"][rung_idx - 1]["policies"][policy]
        for row in prev["slo"]["objectives"]:
            if row["name"] == objective:
                valid = row["pass"] is True
    return {"policy": policy, "objective": objective,
            "rung": rung_idx, "valid": valid}


def validate_traffic_report(report: Dict[str, Any],
                            strict: bool = True) -> List[str]:
    """Structural validation; returns problems (raises when ``strict``)."""
    problems: List[str] = []

    def _fail(msg: str) -> None:
        problems.append(msg)

    if report.get("schema") != TRAFFIC_SCHEMA:
        _fail(f"schema is {report.get('schema')!r}, want {TRAFFIC_SCHEMA!r}")
    for key in ("seed", "arrival", "mix", "slo_spec", "knee_objective",
                "fleet", "ladder", "policies", "rungs", "knees"):
        if key not in report:
            _fail(f"missing top-level key {key!r}")
    if problems:
        if strict:
            raise ValueError("invalid traffic report: "
                             + "; ".join(problems))
        return problems
    ladder = report["ladder"]
    if len(ladder) < 1:
        _fail("empty load ladder")
    if any(b <= a for a, b in zip(ladder, ladder[1:])):
        _fail(f"ladder not strictly increasing: {ladder}")
    policies = report["policies"]
    if len(set(policies)) != len(policies) or not policies:
        _fail(f"bad policy list: {policies}")
    if len(report["rungs"]) != len(ladder):
        _fail(f"{len(report['rungs'])} rungs for {len(ladder)}-step ladder")
    for i, rung in enumerate(report["rungs"]):
        where = f"rung {i}"
        if rung.get("rung") != i:
            _fail(f"{where}: index says {rung.get('rung')}")
        if set(rung.get("policies", {})) != set(policies):
            _fail(f"{where}: policy cells {sorted(rung.get('policies', {}))}"
                  f" != declared {sorted(policies)}")
            continue
        if not rung.get("trace_sha256"):
            _fail(f"{where}: missing trace_sha256")
        for policy, cell in rung["policies"].items():
            pwhere = f"{where}/{policy}"
            missing = _POLICY_CELL_KEYS - set(cell)
            if missing:
                _fail(f"{pwhere}: missing keys {sorted(missing)}")
                continue
            slo = cell["slo"]
            if slo.get("schema") != SLO_SCHEMA:
                _fail(f"{pwhere}: embedded SLO schema "
                      f"{slo.get('schema')!r}")
            accounted = (cell["completed"] + cell["shed"]
                         + cell["expired"] + cell["errors"])
            if accounted != rung.get("n_requests"):
                _fail(f"{pwhere}: outcomes sum {accounted} != offered "
                      f"{rung.get('n_requests')}")
            dhr = cell["deadline_hit_rate"]
            if dhr is not None and not 0.0 <= dhr <= 1.0:
                _fail(f"{pwhere}: deadline_hit_rate {dhr} out of [0,1]")
    for policy in policies:
        if policy not in report["knees"]:
            _fail(f"knees missing policy {policy!r}")
    if strict and problems:
        raise ValueError("invalid traffic report: " + "; ".join(problems))
    return problems


def render_traffic_report(report: Dict[str, Any]) -> str:
    """Human-readable sweep table: one line per (rung, policy)."""
    arrival = report["arrival"]
    lines = [
        f"traffic sweep ({report['schema']}): {arrival['spec']} x "
        f"ladder {report['ladder']}, seed {report['seed']}, "
        f"policies {list(report['policies'])}",
        f"  slo: {report['slo_spec']}  (knee objective: "
        f"{report['knee_objective']})"
        + (f"  chaos: {report['chaos_spec']}" if report.get("chaos_spec")
           else "")
        + (f"  net-chaos: {report['net_chaos_spec']} "
           f"({report['fleet'].get('n_hosts', 1)} hosts)"
           if report.get("net_chaos_spec") else ""),
        f"  {'rung':>4} {'offered':>9} {'policy':<6} {'grade':>5} "
        f"{'attain':>7} {'done':>5} {'shed':>5} {'expired':>7} "
        f"{'dl-hit':>7}",
    ]
    for rung in report["rungs"]:
        for policy in report["policies"]:
            cell = rung["policies"][policy]
            slo = cell["slo"]
            att = slo["attainment"]
            dhr = cell["deadline_hit_rate"]
            lines.append(
                f"  {rung['rung']:>4} {rung['offered_rate']:>8.2f}/s "
                f"{policy:<6} {slo['grade']:>5} "
                f"{('n/a' if att is None else format(att, '.2f')):>7} "
                f"{cell['completed']:>5} {cell['shed']:>5} "
                f"{cell['expired']:>7} "
                f"{('n/a' if dhr is None else format(dhr, '.3f')):>7}")
    knee = report.get("knee")
    if knee is None:
        lines.append(f"  knee: not located ({report['knee_objective']} "
                     f"never fails on this ladder)")
    else:
        shape = "pass->fail" if knee["valid"] else "fails from rung 0"
        lines.append(
            f"  knee: {knee['objective']} under {knee['policy']} first "
            f"fails at rung {knee['rung']} ({shape})")
    return "\n".join(lines)


def dump_report(report: Dict[str, Any]) -> str:
    """Canonical serialization: sorted keys, stable indent, trailing
    newline. Byte-identical across same-seed runs by construction —
    nothing in the report reads a wall clock."""
    return json.dumps(report, sort_keys=True, indent=2) + "\n"
