"""Crash flight recorder (ISSUE 10): a bounded ring of recent trace
records plus registry snapshots, dumped atomically when something goes
wrong — replica crash, breaker trip, watchdog recompile, SIGTERM drain
— or on demand via TelemetryServer's ``/debug/flight``.

Clock discipline: the ring itself stores whatever clock-domain ``ts``
the producing subsystem supplied (virtual seconds under chaos tests).
Only the dump envelope carries a single wall anchor (``wall_ts``) for
humans correlating a dump with logs — that one ``time.time()`` read is
the GL007-sanctioned timestamp-binding idiom.

Durability: each dump is written tmp + ``os.replace`` and then the
manifest (``flight-manifest.json``, same atomic idiom) is rewritten as
the commit point — a reader that follows ``manifest["latest"]`` never
sees a torn dump, mirroring the checkpoint durability manifest design
in ``training/durability.py``.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

FLIGHT_SCHEMA = "mingpt-flight/1"
MANIFEST_SCHEMA = "mingpt-flight-manifest/1"


def _atomic_write(path: str, blob: bytes) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)


class FlightRecorder:
    """Bounded ring + snapshot/dump machinery.

    ``source_providers`` are zero-arg callables returning a list of
    record dicts (e.g. a SpanTracer's ring, which carries log_events);
    ``metrics_providers`` return Prometheus exposition text (the shared
    process registry plus one per replica).  Both are sampled at
    snapshot time, so per-replica providers must be closures that
    survive respawn (resolve ``rep.server`` lazily).
    """

    def __init__(self, capacity: int = 2048, out_dir: Optional[str] = None,
                 max_dumps: int = 32, registry=None):
        self._ring: deque = deque(maxlen=int(capacity))
        self.capacity = int(capacity)
        self.out_dir = out_dir
        self.max_dumps = int(max_dumps)
        self.recorded = 0
        self.dumps_skipped = 0
        self.source_providers: Dict[str, Callable[[], List[dict]]] = {}
        self.metrics_providers: Dict[str, Callable[[], str]] = {}
        self._manifest_entries: List[Dict[str, Any]] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._c_dumps = None
        if registry is not None:
            self._c_dumps = registry.counter(
                "mingpt_flight_dumps_total",
                help="flight-recorder dumps written, by trigger",
                labels=("trigger",))

    # -- the ring -----------------------------------------------------

    def record(self, kind: str, rec: Dict[str, Any]) -> None:
        """Append one record (``ts`` supplied by the producer's clock)."""
        with self._lock:
            self._ring.append({"kind": kind, **rec})
            self.recorded += 1

    @property
    def dropped(self) -> int:
        return max(0, self.recorded - self.capacity)

    # -- snapshot / dump ----------------------------------------------

    def snapshot(self, trigger: str, **attrs) -> Dict[str, Any]:
        """Assemble (but don't persist) a flight record document."""
        with self._lock:
            records = list(self._ring)
            seq = self._seq
        sources: Dict[str, List[dict]] = {}
        for name, fn in sorted(self.source_providers.items()):
            try:
                sources[name] = list(fn())
            except Exception as e:  # a dead provider must not kill a dump
                sources[name] = [{"kind": "provider_error",
                                  "error": repr(e)}]
        metrics: Dict[str, str] = {}
        for name, fn in sorted(self.metrics_providers.items()):
            try:
                metrics[name] = fn()
            except Exception as e:
                metrics[name] = f"# provider_error {e!r}\n"
        wall_ts = time.time()
        doc: Dict[str, Any] = {
            "schema": FLIGHT_SCHEMA, "trigger": trigger, "seq": seq,
            "wall_ts": wall_ts,
            "records": records, "recorded_total": self.recorded,
            "ring_dropped": self.dropped,
            "sources": sources, "metrics": metrics,
        }
        if attrs:
            doc["attrs"] = attrs
        return doc

    def dump(self, trigger: str, **attrs
             ) -> Tuple[Optional[str], Dict[str, Any]]:
        """Snapshot and persist atomically; returns (path, doc).
        ``path`` is None when no out_dir is configured or the dump cap
        was reached (counted in ``dumps_skipped``, never raised)."""
        doc = self.snapshot(trigger, **attrs)
        if self.out_dir is None:
            return None, doc
        with self._lock:
            if len(self._manifest_entries) >= self.max_dumps:
                self.dumps_skipped += 1
                return None, doc
            self._seq += 1
            doc["seq"] = self._seq
            fname = f"flight-{self._seq:04d}-{trigger}.json"
            entry = {"file": fname, "trigger": trigger,
                     "seq": self._seq, "wall_ts": doc["wall_ts"]}
            path = os.path.join(self.out_dir, fname)
            _atomic_write(path, json.dumps(doc, sort_keys=True,
                                           default=repr).encode("utf-8"))
            self._manifest_entries.append(entry)
            manifest = {"schema": MANIFEST_SCHEMA, "latest": fname,
                        "dumps": list(self._manifest_entries)}
            _atomic_write(os.path.join(self.out_dir,
                                       "flight-manifest.json"),
                          json.dumps(manifest, sort_keys=True,
                                     ).encode("utf-8"))
        if self._c_dumps is not None:
            self._c_dumps.labels(trigger=trigger).inc()
        return path, doc


# ---------------------------------------------------------------------
# strict validation / loading
# ---------------------------------------------------------------------


def _fail(msg: str) -> None:
    raise ValueError(f"mingpt-flight/1 validation: {msg}")


def validate_flight_dump(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Strictly validate one dump document.  Every ``metrics`` value
    must pass the strict Prometheus exposition parser — a flight record
    with an unscrapable registry snapshot is evidence lost."""
    from .export import parse_prometheus

    if not isinstance(doc, dict):
        _fail(f"not an object: {type(doc).__name__}")
    if doc.get("schema") != FLIGHT_SCHEMA:
        _fail(f"schema {doc.get('schema')!r} != {FLIGHT_SCHEMA!r}")
    if not isinstance(doc.get("trigger"), str) or not doc["trigger"]:
        _fail("trigger missing or empty")
    for key in ("wall_ts", "recorded_total", "ring_dropped"):
        v = doc.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            _fail(f"{key!r} must be a number >= 0, got {v!r}")
    records = doc.get("records")
    if not isinstance(records, list):
        _fail("records must be a list")
    for i, rec in enumerate(records):
        if not isinstance(rec, dict) or not rec.get("kind"):
            _fail(f"records[{i}] missing kind")
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            _fail(f"records[{i}] missing numeric ts")
    if not isinstance(doc.get("sources"), dict):
        _fail("sources must be an object")
    metrics = doc.get("metrics")
    if not isinstance(metrics, dict):
        _fail("metrics must be an object")
    for name, text in metrics.items():
        if not isinstance(text, str):
            _fail(f"metrics[{name!r}] must be exposition text")
        try:
            parse_prometheus(text)
        except ValueError as e:
            _fail(f"metrics[{name!r}] does not strict-parse: {e}")
    return doc


def load_flight_dir(out_dir: str
                    ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Read + validate the manifest and every dump it lists; returns
    (manifest, [validated docs])."""
    mpath = os.path.join(out_dir, "flight-manifest.json")
    with open(mpath, "r", encoding="utf-8") as fh:
        manifest = json.load(fh)
    if manifest.get("schema") != MANIFEST_SCHEMA:
        _fail(f"manifest schema {manifest.get('schema')!r} != "
              f"{MANIFEST_SCHEMA!r}")
    entries = manifest.get("dumps")
    if not isinstance(entries, list) or not entries:
        _fail("manifest lists no dumps")
    if manifest.get("latest") != entries[-1].get("file"):
        _fail("manifest latest pointer does not match the last entry")
    docs = []
    for entry in entries:
        with open(os.path.join(out_dir, entry["file"]), "r",
                  encoding="utf-8") as fh:
            docs.append(validate_flight_dump(json.load(fh)))
    return manifest, docs
