"""Recompile watchdog (ISSUE 5 tentpole, part 4).

The serving engine's whole design is a *bounded compiled-program family*
— after ``warmup()`` no request may ever trigger a new XLA trace
(tests/test_serving.py asserts trace counts for specific scenarios).
This module turns that one-off test idiom into an always-on runtime
invariant: arm the watchdog on a snapshot of the engine's per-family
trace counts, then ``check()`` at scheduling-round boundaries. Any
post-warmup growth increments
``mingpt_recompiles_total{family=...}``, emits a telemetry event, and —
under the hard-fail knob (constructor arg, or ``MINGPT_RECOMPILE_FATAL=1``
for tests/CI) — raises :class:`RecompileError` so the regression is a
red build, not a silent latency cliff in production.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from mingpt_distributed_tpu.telemetry.registry import MetricsRegistry
from mingpt_distributed_tpu.telemetry.spans import SpanTracer, log_event

__all__ = ["RecompileError", "RecompileWatchdog"]


class RecompileError(RuntimeError):
    """A compiled program family grew after the watchdog was armed."""


class RecompileWatchdog:
    """Counts tracer re-entries on compiled program families.

    ``counts_fn`` returns ``{family_name: trace_count}`` — e.g.
    ``DecodeEngine.compile_counts``. Until :meth:`arm` is called the
    watchdog is dormant (pre-warmup compiles are expected and free to
    happen); after arming, every :meth:`check` reports growth since the
    previous baseline and advances the baseline, so each recompile is
    counted exactly once.
    """

    def __init__(
        self,
        counts_fn: Callable[[], Dict[str, int]],
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[SpanTracer] = None,
        hard_fail: bool = False,
    ):
        if registry is None:
            from mingpt_distributed_tpu import telemetry

            registry = telemetry.get_registry()
        self.tracer = tracer
        self.hard_fail = (
            hard_fail or os.environ.get("MINGPT_RECOMPILE_FATAL") == "1"
        )
        self._counts_fn = counts_fn
        self._baseline: Optional[Dict[str, int]] = None
        self._counter = registry.counter(
            "mingpt_recompiles_total",
            help="post-warmup XLA traces of a compiled program family "
                 "(should stay 0 for the process lifetime)",
            labels=("family",),
        )
        self.recompiles = 0  # total counted by this watchdog instance
        #: optional hook called with {family: new_traces} whenever
        #: growth is detected — serve.py wires the flight recorder's
        #: dump here (ISSUE 10), before any hard-fail raise
        self.on_recompile: Optional[Callable[[Dict[str, int]], None]] = None

    @property
    def armed(self) -> bool:
        return self._baseline is not None

    def arm(self) -> None:
        """Snapshot the current trace counts as the allowed baseline
        (call after warmup, when the full family is pre-traced)."""
        self._baseline = dict(self._counts_fn())

    def check(self) -> int:
        """Count new traces since the last check; 0 when unarmed."""
        if self._baseline is None:
            return 0
        current = dict(self._counts_fn())
        grown = {
            fam: n - self._baseline.get(fam, 0)
            for fam, n in current.items()
            if n > self._baseline.get(fam, 0)
        }
        if not grown:
            return 0
        self._baseline = current  # count each trace exactly once
        total = sum(grown.values())
        self.recompiles += total
        for fam, n in grown.items():
            self._counter.labels(family=fam).inc(n)
            if self.tracer is not None:
                self.tracer.event("recompile", family=fam, new_traces=n)
        detail = ", ".join(f"{fam}+{n}" for fam, n in sorted(grown.items()))
        log_event(
            f"recompile watchdog: {total} post-warmup compile(s) ({detail})",
            tracer=self.tracer,
        )
        if self.on_recompile is not None:
            self.on_recompile(dict(grown))
        if self.hard_fail:
            raise RecompileError(
                f"post-warmup recompile detected: {detail} — the compiled "
                f"program family must be bounded after warmup()"
            )
        return total
