"""SLO engine (ISSUE 10): grade named latency/availability objectives
from *exact* per-request trace durations.

The process histograms (``Histogram.quantile``) answer "roughly where
is p99" from a fixed bucket ladder — the reported quantile is a bucket
*upper bound*, which can overstate the true p99 by the bucket width.
The trace recorder keeps every finished request's exact TTFT and
inter-token gaps, so SLO attainment is computed here from the real
order statistics instead (``exact_quantile``), and shed rate from
outcome counts rather than a sampled counter.

Spec grammar (CLI ``--slo`` and ``parse_slo_spec``)::

    ttft_p99<=0.5,itl_p99<=0.1,shed_rate<=0.05

Metrics: ``ttft_pNN`` (seconds, per-request time-to-first-token),
``itl_pNN`` (seconds, pooled inter-token gaps across all requests),
``shed_rate`` and ``error_rate`` (fractions of all finished requests).
Report schema: ``mingpt-slo/1``.
"""

import math
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

SLO_SCHEMA = "mingpt-slo/1"

DEFAULT_SLO_SPEC = "ttft_p99<=0.5,itl_p99<=0.1,shed_rate<=0.05"

_METRIC_RE = re.compile(r"^(ttft|itl)_p(\d{1,2})$")
_RATE_METRICS = ("shed_rate", "error_rate")

#: grade ladder: fraction of evaluable objectives attained -> letter
_GRADES = ((1.0, "A"), (0.8, "B"), (0.6, "C"), (0.4, "D"))


@dataclass(frozen=True)
class SLObjective:
    """One named objective: ``metric <= threshold``."""

    name: str
    metric: str
    threshold: float

    def __post_init__(self):
        if not _METRIC_RE.match(self.metric) and \
                self.metric not in _RATE_METRICS:
            raise ValueError(
                f"unknown SLO metric {self.metric!r} (want ttft_pNN, "
                f"itl_pNN, shed_rate or error_rate)")
        if not math.isfinite(self.threshold) or self.threshold < 0:
            raise ValueError(
                f"SLO threshold must be finite and >= 0, "
                f"got {self.threshold!r}")


def parse_slo_spec(spec: str) -> Tuple[SLObjective, ...]:
    """Parse ``metric<=threshold[,metric<=threshold...]``; the literal
    spec ``default`` expands to DEFAULT_SLO_SPEC."""
    if spec.strip() == "default":
        spec = DEFAULT_SLO_SPEC
    objectives = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "<=" not in part:
            raise ValueError(f"bad SLO clause {part!r}: want "
                             f"'metric<=threshold'")
        metric, _, raw = part.partition("<=")
        try:
            threshold = float(raw)
        except ValueError:
            raise ValueError(
                f"bad SLO threshold {raw!r} in {part!r}") from None
        metric = metric.strip()
        objectives.append(SLObjective(metric, metric, threshold))
    if not objectives:
        raise ValueError(f"SLO spec {spec!r} names no objectives")
    return tuple(objectives)


def exact_quantile(values: Sequence[float], q: float) -> Optional[float]:
    """Exact order-statistic quantile (nearest-rank on the sorted
    sample) — contrast with Histogram.quantile's bucket upper bound."""
    if not values:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    xs = sorted(float(v) for v in values)
    rank = max(1, math.ceil(q * len(xs)))
    return xs[rank - 1]


def _observe(metric: str, requests: Sequence[Dict[str, Any]],
             ) -> Optional[float]:
    total = len(requests)
    m = _METRIC_RE.match(metric)
    if m is not None:
        field, pct = m.group(1), int(m.group(2)) / 100.0
        if field == "ttft":
            vals = [r["ttft_s"] for r in requests
                    if r.get("ttft_s") is not None]
        else:
            vals = [g for r in requests for g in (r.get("itl_s") or [])]
        return exact_quantile(vals, pct)
    if total == 0:
        return None
    if metric == "shed_rate":
        return sum(1 for r in requests
                   if r.get("outcome") == "shed") / total
    if metric == "error_rate":
        bad = sum(1 for r in requests
                  if r.get("outcome") not in ("length", "eos", "shed"))
        return bad / total
    raise ValueError(f"unknown SLO metric {metric!r}")


def evaluate_slos(requests: Sequence[Dict[str, Any]],
                  objectives: Sequence[SLObjective],
                  ) -> Dict[str, Any]:
    """Grade ``objectives`` against per-request trace summaries (the
    TraceRecorder's ``completed_requests()`` or ``request`` records
    loaded from a mingpt-trace/1 JSONL).  Objectives with no data are
    reported but excluded from the grade."""
    requests = list(requests)
    rows = []
    evaluable = attained = 0
    for obj in objectives:
        observed = _observe(obj.metric, requests)
        ok: Optional[bool] = None
        margin: Optional[float] = None
        if observed is not None:
            ok = observed <= obj.threshold
            margin = obj.threshold - observed
            evaluable += 1
            attained += int(ok)
        rows.append({"name": obj.name, "metric": obj.metric,
                     "threshold": obj.threshold, "observed": observed,
                     "pass": ok, "margin": margin})
    attainment = (attained / evaluable) if evaluable else None
    grade = "n/a"
    if attainment is not None:
        grade = "F"
        for floor, letter in _GRADES:
            if attainment >= floor:
                grade = letter
                break
    outcomes: Dict[str, int] = {}
    for r in requests:
        o = str(r.get("outcome"))
        outcomes[o] = outcomes.get(o, 0) + 1
    return {
        "schema": SLO_SCHEMA,
        "requests": len(requests),
        "outcomes": outcomes,
        "objectives": rows,
        "evaluable": evaluable,
        "attained": attained,
        "attainment": attainment,
        "grade": grade,
    }


def render_slo_report(report: Dict[str, Any]) -> str:
    """Human-readable graded report (one block, stable layout)."""
    lines = [f"SLO report ({report['schema']}): grade "
             f"{report['grade']} — {report['attained']}/"
             f"{report['evaluable']} objectives attained over "
             f"{report['requests']} requests"]
    if report["outcomes"]:
        parts = ", ".join(f"{k}={v}" for k, v in
                          sorted(report["outcomes"].items()))
        lines.append(f"  outcomes: {parts}")
    for row in report["objectives"]:
        if row["observed"] is None:
            lines.append(f"  [ n/a  ] {row['name']:<12} "
                         f"<= {row['threshold']:g}  (no data)")
            continue
        verdict = "PASS" if row["pass"] else "FAIL"
        lines.append(
            f"  [ {verdict} ] {row['name']:<12} <= {row['threshold']:g}"
            f"  observed {row['observed']:.6g}"
            f"  margin {row['margin']:+.6g}")
    return "\n".join(lines)


def load_trace_requests(path: str) -> List[Dict[str, Any]]:
    """Pull the per-request summaries out of a mingpt-trace/1 JSONL
    (strictly validated) for offline SLO evaluation."""
    from .tracing import load_trace_jsonl

    traces = load_trace_jsonl(path)
    return [tr["request"] for tr in traces.values()]
