"""SLO engine (ISSUE 10): grade named latency/availability objectives
from *exact* per-request trace durations.

The process histograms (``Histogram.quantile``) answer "roughly where
is p99" from a fixed bucket ladder — the reported quantile is a bucket
*upper bound*, which can overstate the true p99 by the bucket width.
The trace recorder keeps every finished request's exact TTFT and
inter-token gaps, so SLO attainment is computed here from the real
order statistics instead (``exact_quantile``), and shed rate from
outcome counts rather than a sampled counter.

Spec grammar (CLI ``--slo`` and ``parse_slo_spec``)::

    ttft_p99<=0.5,itl_p99<=0.1,shed_rate<=0.05

Metrics: ``ttft_pNN`` (seconds, per-request time-to-first-token),
``itl_pNN`` (seconds, pooled inter-token gaps across all requests),
``recovery_pNN`` (seconds, per-request time from a replica fault to
the first token the replacement emitted — the recovery tail; only
requests a crash actually re-routed carry the sample), ``shed_rate``
and ``error_rate`` (fractions of all finished requests).
Report schema: ``mingpt-slo/1``.
"""

import math
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

SLO_SCHEMA = "mingpt-slo/1"

DEFAULT_SLO_SPEC = "ttft_p99<=0.5,itl_p99<=0.1,shed_rate<=0.05"

_METRIC_RE = re.compile(r"^(ttft|itl|recovery)_p(\d{1,2})$")
_RATE_METRICS = ("shed_rate", "error_rate")

#: grade ladder: fraction of evaluable objectives attained -> letter
_GRADES = ((1.0, "A"), (0.8, "B"), (0.6, "C"), (0.4, "D"))


@dataclass(frozen=True)
class SLObjective:
    """One named objective: ``metric <= threshold``."""

    name: str
    metric: str
    threshold: float

    def __post_init__(self):
        if not _METRIC_RE.match(self.metric) and \
                self.metric not in _RATE_METRICS:
            raise ValueError(
                f"unknown SLO metric {self.metric!r} (want ttft_pNN, "
                f"itl_pNN, recovery_pNN, shed_rate or error_rate)")
        if not math.isfinite(self.threshold) or self.threshold < 0:
            raise ValueError(
                f"SLO threshold must be finite and >= 0, "
                f"got {self.threshold!r}")


def parse_slo_spec(spec: str) -> Tuple[SLObjective, ...]:
    """Parse ``metric<=threshold[,metric<=threshold...]``; the literal
    spec ``default`` expands to DEFAULT_SLO_SPEC."""
    if spec.strip() == "default":
        spec = DEFAULT_SLO_SPEC
    objectives = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "<=" not in part:
            raise ValueError(f"bad SLO clause {part!r}: want "
                             f"'metric<=threshold'")
        metric, _, raw = part.partition("<=")
        try:
            threshold = float(raw)
        except ValueError:
            raise ValueError(
                f"bad SLO threshold {raw!r} in {part!r}") from None
        metric = metric.strip()
        objectives.append(SLObjective(metric, metric, threshold))
    if not objectives:
        raise ValueError(f"SLO spec {spec!r} names no objectives")
    return tuple(objectives)


def exact_quantile(values: Sequence[float], q: float) -> Optional[float]:
    """Exact order-statistic quantile (nearest-rank on the sorted
    sample) — contrast with Histogram.quantile's bucket upper bound."""
    if not values:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    xs = sorted(float(v) for v in values)
    rank = max(1, math.ceil(q * len(xs)))
    return xs[rank - 1]


def _observe(metric: str, requests: Sequence[Dict[str, Any]],
             ) -> Optional[float]:
    total = len(requests)
    m = _METRIC_RE.match(metric)
    if m is not None:
        field, pct = m.group(1), int(m.group(2)) / 100.0
        if field == "ttft":
            vals = [r["ttft_s"] for r in requests
                    if r.get("ttft_s") is not None]
        elif field == "recovery":
            # only requests a fault actually re-routed carry the sample
            # (fault observed -> first token from the replacement)
            vals = [r["recovery_s"] for r in requests
                    if r.get("recovery_s") is not None]
        else:
            vals = [g for r in requests for g in (r.get("itl_s") or [])]
        return exact_quantile(vals, pct)
    if total == 0:
        return None
    if metric == "shed_rate":
        return sum(1 for r in requests
                   if r.get("outcome") == "shed") / total
    if metric == "error_rate":
        bad = sum(1 for r in requests
                  if r.get("outcome") not in ("length", "eos", "shed"))
        return bad / total
    raise ValueError(f"unknown SLO metric {metric!r}")


def evaluate_slos(requests: Sequence[Dict[str, Any]],
                  objectives: Sequence[SLObjective],
                  ) -> Dict[str, Any]:
    """Grade ``objectives`` against per-request trace summaries (the
    TraceRecorder's ``completed_requests()`` or ``request`` records
    loaded from a mingpt-trace/1 JSONL).  Objectives with no data are
    reported but excluded from the grade."""
    requests = list(requests)
    rows = []
    evaluable = attained = 0
    for obj in objectives:
        observed = _observe(obj.metric, requests)
        ok: Optional[bool] = None
        margin: Optional[float] = None
        if observed is not None:
            ok = observed <= obj.threshold
            margin = obj.threshold - observed
            evaluable += 1
            attained += int(ok)
        rows.append({"name": obj.name, "metric": obj.metric,
                     "threshold": obj.threshold, "observed": observed,
                     "pass": ok, "margin": margin})
    attainment = (attained / evaluable) if evaluable else None
    grade = "n/a"
    if attainment is not None:
        grade = "F"
        for floor, letter in _GRADES:
            if attainment >= floor:
                grade = letter
                break
    outcomes: Dict[str, int] = {}
    for r in requests:
        o = str(r.get("outcome"))
        outcomes[o] = outcomes.get(o, 0) + 1
    return {
        "schema": SLO_SCHEMA,
        "requests": len(requests),
        "outcomes": outcomes,
        "objectives": rows,
        "evaluable": evaluable,
        "attained": attained,
        "attainment": attainment,
        "grade": grade,
    }


def render_slo_report(report: Dict[str, Any]) -> str:
    """Human-readable graded report (one block, stable layout)."""
    lines = [f"SLO report ({report['schema']}): grade "
             f"{report['grade']} — {report['attained']}/"
             f"{report['evaluable']} objectives attained over "
             f"{report['requests']} requests"]
    if report["outcomes"]:
        parts = ", ".join(f"{k}={v}" for k, v in
                          sorted(report["outcomes"].items()))
        lines.append(f"  outcomes: {parts}")
    for row in report["objectives"]:
        if row["observed"] is None:
            lines.append(f"  [ n/a  ] {row['name']:<12} "
                         f"<= {row['threshold']:g}  (no data)")
            continue
        verdict = "PASS" if row["pass"] else "FAIL"
        lines.append(
            f"  [ {verdict} ] {row['name']:<12} <= {row['threshold']:g}"
            f"  observed {row['observed']:.6g}"
            f"  margin {row['margin']:+.6g}")
    return "\n".join(lines)


def diff_slo_reports(a: Dict[str, Any], b: Dict[str, Any],
                     ) -> Dict[str, Any]:
    """Per-objective delta between two mingpt-slo/1 reports (e.g. two
    ``serve.py --slo-json`` runs, or one run before/after a change).

    Objectives are matched by name; rows present in only one report get
    ``observed`` None on the other side and no delta. ``delta`` is
    ``b.observed - a.observed`` (negative = b is better for these
    lower-is-better metrics); ``verdict`` summarizes the pass/fail
    transition (``same``, ``fixed``, ``regressed``, ``n/a``)."""
    for label, rep in (("a", a), ("b", b)):
        if rep.get("schema") != SLO_SCHEMA:
            raise ValueError(
                f"report {label} is not {SLO_SCHEMA}: "
                f"schema={rep.get('schema')!r}")
    rows_a = {row["name"]: row for row in a["objectives"]}
    rows_b = {row["name"]: row for row in b["objectives"]}
    names = list(rows_a)
    names.extend(n for n in rows_b if n not in rows_a)
    out_rows = []
    for name in names:
        ra, rb = rows_a.get(name), rows_b.get(name)
        oa = ra.get("observed") if ra else None
        ob = rb.get("observed") if rb else None
        delta = (ob - oa) if (oa is not None and ob is not None) else None
        pa = ra.get("pass") if ra else None
        pb = rb.get("pass") if rb else None
        if pa is None or pb is None:
            verdict = "n/a"
        elif pa == pb:
            verdict = "same"
        elif pb:
            verdict = "fixed"
        else:
            verdict = "regressed"
        out_rows.append({
            "name": name,
            "metric": (ra or rb)["metric"],
            "threshold": (ra or rb)["threshold"],
            "observed_a": oa,
            "observed_b": ob,
            "delta": delta,
            "pass_a": pa,
            "pass_b": pb,
            "verdict": verdict,
        })
    return {
        "schema": f"{SLO_SCHEMA}-diff",
        "requests_a": a["requests"],
        "requests_b": b["requests"],
        "grade_a": a["grade"],
        "grade_b": b["grade"],
        "objectives": out_rows,
    }


def render_slo_diff(diff: Dict[str, Any]) -> str:
    """Human-readable per-objective delta table for ``diff_slo_reports``."""
    lines = [f"SLO diff ({diff['schema']}): grade {diff['grade_a']} -> "
             f"{diff['grade_b']}  (requests {diff['requests_a']} -> "
             f"{diff['requests_b']})"]
    lines.append(f"  {'objective':<14} {'threshold':>10} {'a':>12} "
                 f"{'b':>12} {'delta':>12}  verdict")
    for row in diff["objectives"]:

        def _cell(v: Optional[float]) -> str:
            return "n/a" if v is None else f"{v:.6g}"

        lines.append(
            f"  {row['name']:<14} {row['threshold']:>10g} "
            f"{_cell(row['observed_a']):>12} {_cell(row['observed_b']):>12} "
            f"{_cell(row['delta']):>12}  {row['verdict']}")
    return "\n".join(lines)


def load_trace_requests(path: str) -> List[Dict[str, Any]]:
    """Pull the per-request summaries out of a mingpt-trace/1 JSONL
    (strictly validated) for offline SLO evaluation."""
    from .tracing import load_trace_jsonl

    traces = load_trace_jsonl(path)
    return [tr["request"] for tr in traces.values()]
