"""Unified telemetry subsystem (ISSUE 5): one registry, spans, exporters,
recompile watchdog.

The whole stack reports through this package:

* ``registry``  — counters / gauges / fixed-ladder histograms in ONE
  :class:`MetricsRegistry`; ``RateWindow`` (the shared windowed-rate
  plumbing) lives here too.
* ``peaks``     — the single roofline table (``PEAK_FLOPS`` /
  ``PEAK_HBM_BYTES``) both ``training/metrics.py`` and ``bench.py``
  consume.
* ``spans``     — monotonic-clock nested spans in a bounded ring with an
  optional JSONL sink, plus ``log_event`` (prefixed, attributable
  replacement for bare prints in multi-process paths).
* ``export``    — Prometheus text exposition + strict parser, the
  versioned JSONL event schema, and the stdlib ``/metrics`` +
  ``/healthz`` HTTP server.
* ``watchdog``  — post-warmup recompile detection over the serving
  engine's compiled program families.
* ``tracing``   — request-scoped traces (ISSUE 10): a TraceContext
  minted at submit and propagated router → replica → scheduler, spans
  and emit events collected per request, sampled ``mingpt-trace/1``
  JSONL export with a strict loader.
* ``flightrec`` — bounded flight-recorder ring dumped atomically on
  crash / breaker trip / recompile / drain and via ``/debug/flight``.
* ``slo``       — graded SLO reports from exact per-request trace
  durations (not histogram-bucket upper bounds).

Process-wide defaults: :func:`get_registry` / :func:`get_tracer` are the
lazily-created singletons entry points (``train.py``, ``serve.py``) wire
into every logger so one scrape page exposes the whole process. Library
classes (``MetricsLogger``, ``ServingMetrics``) default to private
instances for test isolation — pass the globals explicitly to unify.
"""

from __future__ import annotations

from typing import Optional

from mingpt_distributed_tpu.telemetry.attribution import (
    ATTRIB_SCHEMA,
    HBMLedger,
    ProgramLedger,
    build_attrib_report,
    dump_attrib_report,
    kv_cache_bytes,
    per_device_tree_bytes,
    render_attrib_report,
    timed_aot_compile,
    tree_bytes,
    validate_attrib_report,
)
from mingpt_distributed_tpu.telemetry.export import (
    SCHEMA_VERSION,
    JsonlEventSink,
    TelemetryServer,
    merge_fleet_pages,
    parse_prometheus,
    register_build_info,
    render_fleet_prometheus,
    render_prometheus,
)
from mingpt_distributed_tpu.telemetry.flightrec import (
    FLIGHT_SCHEMA,
    FlightRecorder,
    load_flight_dir,
    validate_flight_dump,
)
from mingpt_distributed_tpu.telemetry.peaks import (
    PEAK_FLOPS,
    PEAK_HBM_BYTES,
    PEAK_HBM_CAPACITY,
    peak_flops_per_chip,
    peak_hbm_bytes_per_chip,
    peak_hbm_capacity_per_chip,
)
from mingpt_distributed_tpu.telemetry.registry import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricFamily,
    MetricsRegistry,
    RateWindow,
)
from mingpt_distributed_tpu.telemetry.slo import (
    SLO_SCHEMA,
    SLObjective,
    diff_slo_reports,
    evaluate_slos,
    exact_quantile,
    parse_slo_spec,
    render_slo_diff,
    render_slo_report,
)
from mingpt_distributed_tpu.telemetry.spans import (
    SpanTracer,
    log_event,
    process_index,
)
from mingpt_distributed_tpu.telemetry.tracing import (
    TRACE_SCHEMA,
    TraceContext,
    TraceRecorder,
    load_trace_jsonl,
    trace_baggage,
    trace_sink,
    validate_trace_records,
)
from mingpt_distributed_tpu.telemetry.watchdog import (
    RecompileError,
    RecompileWatchdog,
)

__all__ = [
    "ATTRIB_SCHEMA",
    "FLIGHT_SCHEMA",
    "SCHEMA_VERSION",
    "SLO_SCHEMA",
    "TRACE_SCHEMA",
    "LATENCY_BUCKETS_S",
    "PEAK_FLOPS",
    "PEAK_HBM_BYTES",
    "PEAK_HBM_CAPACITY",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "HBMLedger",
    "Histogram",
    "JsonlEventSink",
    "MetricFamily",
    "MetricsRegistry",
    "ProgramLedger",
    "RateWindow",
    "RecompileError",
    "RecompileWatchdog",
    "SLObjective",
    "SpanTracer",
    "TelemetryServer",
    "TraceContext",
    "TraceRecorder",
    "build_attrib_report",
    "diff_slo_reports",
    "dump_attrib_report",
    "evaluate_slos",
    "exact_quantile",
    "get_registry",
    "get_tracer",
    "kv_cache_bytes",
    "load_flight_dir",
    "load_trace_jsonl",
    "log_event",
    "merge_fleet_pages",
    "parse_prometheus",
    "parse_slo_spec",
    "peak_flops_per_chip",
    "peak_hbm_bytes_per_chip",
    "peak_hbm_capacity_per_chip",
    "process_index",
    "register_build_info",
    "render_attrib_report",
    "render_fleet_prometheus",
    "render_prometheus",
    "render_slo_diff",
    "render_slo_report",
    "timed_aot_compile",
    "trace_baggage",
    "trace_sink",
    "per_device_tree_bytes",
    "tree_bytes",
    "validate_attrib_report",
    "validate_trace_records",
]

_registry: Optional[MetricsRegistry] = None
_tracer: Optional[SpanTracer] = None


def get_registry() -> MetricsRegistry:
    """The process-wide registry every entry point exports from."""
    global _registry
    if _registry is None:
        _registry = MetricsRegistry()
    return _registry


def get_tracer() -> SpanTracer:
    """The process-wide tracer, gated to process 0 (single-writer, the
    same convention as MetricsLogger) — other processes get a disabled
    tracer whose spans are no-ops."""
    global _tracer
    if _tracer is None:
        _tracer = SpanTracer(enabled=process_index() == 0)
    return _tracer
