"""One metrics registry for the whole stack (ISSUE 5 tentpole, part 2).

Before this subsystem the repo had three disconnected observability
surfaces — ``training/metrics.py::MetricsLogger``,
``serving/metrics.py::ServingMetrics`` and ``tools/trace_summary.py`` —
each owning private dicts and ad-hoc accumulators with no shared export
path. Here both loggers *register typed instruments* (counters, gauges,
histograms with fixed bucket ladders) into a :class:`MetricsRegistry`,
and every exporter (Prometheus text exposition, JSONL events — see
``telemetry/export.py``) reads the same registry.

Instrument semantics follow the Prometheus data model:

* **Counter** — monotonically non-decreasing; ``inc(n)`` with ``n >= 0``.
* **Gauge** — a value that can go anywhere; ``set(v)``.
* **Histogram** — fixed upper-bound bucket ladder chosen at registration
  (never per-observation); exposition renders cumulative ``_bucket``
  series plus ``_sum``/``_count``.

Families may declare label names; ``family.labels(k=v)`` returns (and
memoises) the child for that label combination. Label-less families
proxy the instrument ops directly (``family.inc(...)``).

Isolation convention (mirrors prometheus_client's ``registry=`` idiom):
library classes default to a *fresh private* registry so unit tests stay
independent, while entry points (``serve.py``, ``train.py``) pass the
process-wide registry from ``telemetry.get_registry()`` so one scrape
page exposes the whole stack.

Naming convention (docs/architecture.md "Telemetry"): every metric is
``mingpt_<subsystem>_<what>[_total|_seconds]`` — subsystems ``train``,
``serve``, and ``telemetry`` itself (the recompile watchdog).
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricFamily",
    "MetricsRegistry",
    "RateWindow",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default latency ladder (seconds): 1ms .. 10s in a 1-2.5-5 progression.
#: Fixed at registration — the whole point of a bucket ladder is that a
#: scrape is comparable across time and across processes.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class RateWindow:
    """Windowed rate of a monotonically increasing marker (steps, tokens).

    ``observe(marker)`` returns the marker's change per second since the
    previous call, or None on the first call / when the marker did not
    advance / when no wall time elapsed (the zero-elapsed guard — two
    observations inside one clock tick must not divide by zero). Shared
    plumbing between the training MetricsLogger (steps/sec → tokens/sec/
    MFU) and ServingMetrics (tokens/sec), so both report rates over the
    same kind of log window.
    """

    def __init__(self) -> None:
        self._last: Optional[Tuple[float, float]] = None

    def observe(self, marker: float, now: Optional[float] = None) -> Optional[float]:
        if now is None:
            now = time.perf_counter()
        rate = None
        if self._last is not None:
            last_t, last_m = self._last
            if marker > last_m and now > last_t:
                rate = (marker - last_m) / (now - last_t)
        self._last = (now, marker)
        return rate


class Counter:
    """Monotonic counter. ``value`` is read by exporters."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter decrease not allowed (inc({n}))")
        with self._lock:
            self.value += n


class Gauge:
    """Set-anywhere gauge."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-ladder histogram: per-bucket counts (non-cumulative in
    memory; the exposition layer renders the cumulative ``le`` form),
    plus ``sum``/``count`` so means are derivable without a private
    accumulator next to the histogram."""

    __slots__ = ("uppers", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: Sequence[float], lock: threading.Lock):
        uppers = tuple(float(b) for b in buckets)
        if not uppers:
            raise ValueError("histogram needs at least one bucket bound")
        if list(uppers) != sorted(set(uppers)):
            raise ValueError(f"bucket bounds must strictly increase: {uppers}")
        self.uppers = uppers
        self.counts = [0] * (len(uppers) + 1)  # +1: the implicit +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            self.counts[bisect.bisect_left(self.uppers, v)] += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)], ending with (inf, count)."""
        out, acc = [], 0
        with self._lock:
            for u, c in zip(self.uppers, self.counts):
                acc += c
                out.append((u, acc))
            out.append((float("inf"), self.count))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Ladder-resolution quantile estimate: the smallest bucket upper
        bound at which the cumulative count reaches ``q * count`` — a
        conservative (upper-bound) estimate, which is the right bias for
        SLO gating: a replica is flagged slow no later than its true
        quantile crossing the threshold. None with no observations;
        ``inf`` when the quantile falls in the overflow bucket."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {q}")
        with self._lock:
            if not self.count:
                return None
            need = q * self.count
            acc = 0
            for u, c in zip(self.uppers, self.counts):
                acc += c
                if acc >= need:
                    return u
        return float("inf")


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric with a kind, optional label names, and one child
    instrument per label-value combination. Label-less families proxy the
    child ops (``inc``/``set``/``observe``) and read-outs directly."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
        lock: Optional[threading.Lock] = None,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        if kind == "histogram":
            self.buckets = tuple(
                LATENCY_BUCKETS_S if buckets is None else buckets)
        else:
            self.buckets = None
        self._lock = lock or threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.label_names:
            self._children[()] = self._new_child()
        elif kind == "histogram":
            Histogram(self.buckets, self._lock)  # validate the ladder now,
            # not at the first labels() call deep inside serving code

    def _new_child(self):
        if self.kind == "histogram":
            return Histogram(self.buckets, self._lock)
        return _KINDS[self.kind](self._lock)

    def labels(self, **labelvalues: object):
        if set(labelvalues) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labelvalues)} != declared "
                f"{sorted(self.label_names)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
        return child

    @property
    def _default(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; "
                f"use .labels(...)"
            )
        return self._children[()]

    # label-less proxies (AttributeError on kind mismatch is deliberate)
    def inc(self, n: float = 1.0) -> None:
        self._default.inc(n)

    def set(self, v: float) -> None:
        self._default.set(v)

    def observe(self, v: float) -> None:
        self._default.observe(v)

    def cumulative(self) -> List[Tuple[float, int]]:
        return self._default.cumulative()

    def quantile(self, q: float) -> Optional[float]:
        return self._default.quantile(q)

    @property
    def value(self) -> float:
        return self._default.value

    @property
    def sum(self) -> float:
        return self._default.sum

    @property
    def count(self) -> int:
        return self._default.count

    def children(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._children.items())
        return [
            (dict(zip(self.label_names, key)), child) for key, child in items
        ]


class MetricsRegistry:
    """Get-or-create instrument registry; the unit every exporter reads.

    Re-registering an existing name with identical (kind, labels,
    buckets) returns the existing family — so independent modules can
    name the same metric without coordination — while a conflicting
    redefinition raises (silent kind drift would corrupt dashboards).
    """

    def __init__(self) -> None:
        self._families: Dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    def _get_or_create(
        self,
        name: str,
        kind: str,
        help: str,
        labels: Sequence[str],
        buckets: Optional[Sequence[float]] = None,
    ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if (
                    fam.kind != kind
                    or fam.label_names != tuple(labels)
                    or (kind == "histogram"
                        and buckets is not None
                        and fam.buckets != tuple(buckets))
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names} — conflicting "
                        f"redefinition as {kind}{tuple(labels)}"
                    )
                return fam
            fam = MetricFamily(name, kind, help, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> MetricFamily:
        return self._get_or_create(name, "counter", help, labels)

    def gauge(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> MetricFamily:
        return self._get_or_create(name, "gauge", help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
        labels: Sequence[str] = (),
    ) -> MetricFamily:
        return self._get_or_create(name, "histogram", help, labels, buckets)

    def collect(self) -> Iterable[MetricFamily]:
        """Families sorted by name — the exposition order."""
        with self._lock:
            fams = list(self._families.values())
        return sorted(fams, key=lambda f: f.name)
