"""Performance attribution (ISSUE 13 tentpole): where the flops, bytes
and compile seconds actually go.

Two ledgers, one report (``mingpt-attrib/1``):

* :class:`ProgramLedger` — every lifetime-compiled executable family
  (prefill buckets, decode step, spec verify/draft, train step, zero
  update) registers at compile time with its compile wall-time and the
  XLA ``cost_analysis()`` FLOPs / bytes-accessed, then accumulates
  invocation counts and sampled device wall-time from the scheduling
  loop's existing clock measurements. Per family the report derives a
  roofline position: arithmetic intensity, the roofline-*expected* MFU
  ceiling (``min(1, intensity / machine_balance)``) and the *measured*
  MFU, both against ``telemetry/peaks.py`` — so a family reading 0.04
  measured vs 0.9 expected is leaving compute on the table, while 0.04
  vs 0.05 is simply bandwidth-bound decode behaving as the roofline
  says it must.
* :class:`HBMLedger` — exact bytes-by-owner computed from shapes and
  dtypes (params, optimizer state zero_dp-aware via
  ``parallel/zero.py:opt_moment_bytes``, KV slot pool, prefix store,
  draft pool), a ``jax.live_arrays()`` leak audit (live but unowned
  bytes), and a headroom gauge against the chip's HBM capacity.

Clock discipline: this module NEVER reads a clock. Compile timing goes
through :func:`timed_aot_compile`'s injected ``clock`` callable and
invocation timing arrives as already-measured durations from callers
that own a clock seam (the scheduler's ``self.clock``), so GL007 holds
outright and attribution reports on ``VirtualClock`` are
byte-deterministic (``dump_attrib_report`` sorts keys; the
``jax.live_arrays()`` audit is excluded from the report by default
because leftover buffers from a previous run are process state, not
report state).

AOT registration is watchdog-safe: ``jit_fn.lower(args).compile()``
does not populate the jit call cache (``_cache_size()`` is unchanged),
so registering a family next to an armed :class:`RecompileWatchdog`
never trips it.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mingpt_distributed_tpu.telemetry.peaks import (
    peak_flops_per_chip,
    peak_hbm_bytes_per_chip,
    peak_hbm_capacity_per_chip,
)
from mingpt_distributed_tpu.telemetry.registry import MetricsRegistry

__all__ = [
    "ATTRIB_SCHEMA",
    "HBMLedger",
    "ProgramLedger",
    "build_attrib_report",
    "dump_attrib_report",
    "kv_cache_bytes",
    "per_device_tree_bytes",
    "render_attrib_report",
    "timed_aot_compile",
    "tree_bytes",
    "validate_attrib_report",
]

ATTRIB_SCHEMA = "mingpt-attrib/1"


# ---------------------------------------------------------------------
# cost_analysis plumbing
# ---------------------------------------------------------------------


def _cost_to_flops_bytes(
    cost: Any,
) -> Tuple[Optional[float], Optional[float]]:
    """Normalise ``Compiled.cost_analysis()`` output. Backends disagree
    on the container (CPU returns a list with one dict per program,
    some return the dict bare, some return None); the keys are stable:
    ``"flops"`` and ``"bytes accessed"``."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None, None
    flops = cost.get("flops")
    byts = cost.get("bytes accessed")
    return (
        float(flops) if flops is not None else None,
        float(byts) if byts is not None else None,
    )


def timed_aot_compile(
    jit_fn: Any,
    args: Tuple[Any, ...],
    clock: Callable[[], float],
    kwargs: Optional[Dict[str, Any]] = None,
) -> Tuple[float, Optional[float], Optional[float]]:
    """AOT-lower and compile a jitted callable against ``args``,
    returning ``(compile_seconds, flops, bytes_accessed)``.

    Timing is read from the injected ``clock`` only (on a VirtualClock
    the duration is exactly 0.0 — deterministic, which the byte-identity
    selftest relies on). The AOT path shares the backend compilation
    cache with the normal call path but does NOT insert into the jit
    call cache, so ``_cache_size()``-based recompile accounting (the
    watchdog, ``compile_counts`` selftests) is unaffected.
    """
    t0 = clock()
    compiled = jit_fn.lower(*args, **(kwargs or {})).compile()
    t1 = clock()
    try:
        cost = compiled.cost_analysis()
    except Exception:  # backends without cost models still attribute time
        cost = None
    flops, byts = _cost_to_flops_bytes(cost)
    return t1 - t0, flops, byts


# ---------------------------------------------------------------------
# Program ledger
# ---------------------------------------------------------------------


class _ProgramStats:
    __slots__ = ("compiles", "compile_s", "flops", "bytes_accessed",
                 "calls", "device_s")

    def __init__(self) -> None:
        self.compiles = 0
        self.compile_s = 0.0
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.calls = 0
        self.device_s = 0.0


class ProgramLedger:
    """Per-program-family cost ledger.

    Families register once at compile time (``observe_compile`` or the
    ``register_aot`` convenience that wraps :func:`timed_aot_compile`)
    and accumulate invocation samples (``observe_call``) from whatever
    loop owns the clock. ``variant`` distinguishes members of a family
    that compile separately (prefill bucket sizes, zero vs dense train
    step) while keeping one logical row group.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._programs: Dict[Tuple[str, str], _ProgramStats] = {}
        r = self.registry
        labels = ("family", "variant")
        self._g_flops = r.gauge(
            "mingpt_attrib_flops",
            help="cost_analysis FLOPs of one invocation of this program",
            labels=labels)
        self._g_bytes = r.gauge(
            "mingpt_attrib_bytes_accessed",
            help="cost_analysis bytes accessed by one invocation",
            labels=labels)
        self._g_compile = r.gauge(
            "mingpt_attrib_compile_seconds",
            help="cumulative compile wall-time of this program family",
            labels=labels)
        self._c_calls = r.counter(
            "mingpt_attrib_calls_total",
            help="invocations observed for this program family",
            labels=labels)
        self._c_device = r.counter(
            "mingpt_attrib_device_seconds_total",
            help="sampled device wall-time spent in this program family",
            labels=labels)
        self._g_mfu = r.gauge(
            "mingpt_attrib_mfu",
            help="measured model FLOPs utilisation vs the chip peak "
                 "(absent off-TPU: no peak table row)",
            labels=labels)

    # -- registration --------------------------------------------------
    def observe_compile(
        self,
        family: str,
        compile_s: float,
        flops: Optional[float],
        bytes_accessed: Optional[float],
        variant: str = "",
    ) -> None:
        st = self._programs.setdefault((family, variant), _ProgramStats())
        st.compiles += 1
        st.compile_s += float(compile_s)
        # cost_analysis is a property of the program, not the call: keep
        # the latest non-None reading (re-registration is idempotent)
        if flops is not None:
            st.flops = float(flops)
        if bytes_accessed is not None:
            st.bytes_accessed = float(bytes_accessed)
        lab = dict(family=family, variant=variant)
        self._g_compile.labels(**lab).set(st.compile_s)
        if st.flops is not None:
            self._g_flops.labels(**lab).set(st.flops)
        if st.bytes_accessed is not None:
            self._g_bytes.labels(**lab).set(st.bytes_accessed)
        # pre-touch the call counters so a registered-but-never-invoked
        # family is still visible on the scrape page at 0
        self._c_calls.labels(**lab)
        self._c_device.labels(**lab)

    def register_aot(
        self,
        family: str,
        jit_fn: Any,
        args: Tuple[Any, ...],
        clock: Callable[[], float],
        variant: str = "",
        kwargs: Optional[Dict[str, Any]] = None,
    ) -> None:
        t0 = clock()
        lowered = jit_fn.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
        t1 = clock()
        try:
            cost = compiled.cost_analysis()
        except Exception:  # backends without cost models still attribute
            cost = None
        flops, byts = _cost_to_flops_bytes(cost)
        self.observe_compile(family, t1 - t0, flops, byts, variant=variant)
        self.observe_lowered(family, variant, lowered, compiled)

    def observe_lowered(
        self, family: str, variant: str, lowered: Any, compiled: Any,
    ) -> None:
        """Hook: every ``register_aot`` hands the lowered + compiled
        artifacts here before dropping them. The base ledger keeps only
        the cost numbers (holding HLO text for every family would pin
        megabytes for the server's lifetime); subclasses that audit the
        lowered programs — ``analysis/hlo_audit.AuditLedger`` — override
        this to capture text, aliasing and output shardings. Registration
        seams stay unchanged: anything that knows how to
        ``register_attrib`` against a ProgramLedger is auditable for
        free."""

    # -- invocation sampling -------------------------------------------
    def observe_call(
        self, family: str, seconds: float, variant: str = "", n: int = 1,
    ) -> None:
        st = self._programs.setdefault((family, variant), _ProgramStats())
        st.calls += int(n)
        st.device_s += float(seconds)
        lab = dict(family=family, variant=variant)
        self._c_calls.labels(**lab).inc(int(n))
        self._c_device.labels(**lab).inc(float(seconds))
        mfu = _measured_mfu(st, peak_flops_per_chip())
        if mfu is not None:
            self._g_mfu.labels(**lab).set(mfu)

    # -- readout -------------------------------------------------------
    def families(self) -> List[str]:
        return sorted({fam for fam, _ in self._programs})

    def rows(self) -> List[Dict[str, Any]]:
        """One report row per (family, variant), sorted; roofline fields
        derived against the peak tables (None off-TPU)."""
        peak_f = peak_flops_per_chip()
        peak_bw = peak_hbm_bytes_per_chip()
        out = []
        for (family, variant) in sorted(self._programs):
            st = self._programs[(family, variant)]
            ai = None
            if st.flops is not None and st.bytes_accessed:
                ai = st.flops / st.bytes_accessed
            expected_mfu = None
            if ai is not None and peak_f and peak_bw:
                # roofline ceiling: compute-bound families saturate at 1,
                # bandwidth-bound ones at intensity / machine-balance
                expected_mfu = min(1.0, ai / (peak_f / peak_bw))
            out.append({
                "family": family,
                "variant": variant,
                "compiles": st.compiles,
                "compile_s": st.compile_s,
                "flops": st.flops,
                "bytes_accessed": st.bytes_accessed,
                "calls": st.calls,
                "device_s": st.device_s,
                "arith_intensity": ai,
                "expected_mfu": expected_mfu,
                "measured_mfu": _measured_mfu(st, peak_f),
            })
        return out


def _measured_mfu(st: _ProgramStats, peak_f: Optional[float],
                  ) -> Optional[float]:
    if st.flops is None or not peak_f or st.device_s <= 0 or st.calls < 1:
        return None
    return (st.flops * st.calls / st.device_s) / peak_f


# ---------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------


def tree_bytes(tree: Any) -> int:
    """Analytic bytes of a pytree from shapes/dtypes alone — works on
    device arrays, numpy arrays and ShapeDtypeStructs alike (no
    device-side readout, so it is exact even for donated buffers)."""
    import jax  # lazy: telemetry must import without a backend

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return int(total)


def per_device_tree_bytes(tree: Any) -> int:
    """Analytic bytes of a pytree *on one device* — each leaf contributes
    its shard size (``sharding.shard_shape``), so a head-sharded KV pool
    counts ``total / tp`` and a replicated or single-device leaf counts
    its full size (ISSUE 14). Analytic like ``tree_bytes`` (no
    ``addressable_shards`` readout): exact for donated buffers and
    byte-deterministic across runs. Leaves without a sharding (numpy
    arrays, ShapeDtypeStructs) fall back to full size."""
    import jax  # lazy: telemetry must import without a backend

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(tuple(shape))
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return int(total)


def kv_cache_bytes(cfg: Any, n_slots: int, dtype: Any = None) -> int:
    """Exact bytes of one slot-pool KV cache: the two
    ``(n_layer, n_slots, block_size, kv_heads, head_dim)`` buffers of
    ``models/generate.init_cache``."""
    elems = (int(cfg.n_layer) * int(n_slots) * int(cfg.block_size)
             * int(cfg.kv_heads) * int(cfg.head_dim))
    itemsize = np.dtype(dtype if dtype is not None else cfg.dtype).itemsize
    return 2 * elems * itemsize


class HBMLedger:
    """Bytes-by-owner HBM accounting plus the live-array leak audit.

    ``account(owner, nbytes)`` is declarative (set, not add): owners
    re-account as their pools change, and the ledger is the sum of the
    latest declarations. ``audit()`` compares the owned total against
    what the runtime actually holds (``jax.live_arrays()``) — a growing
    unattributed residue is the leak signal the report is for.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        capacity_bytes: Optional[float] = None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.capacity_bytes = (capacity_bytes if capacity_bytes is not None
                               else peak_hbm_capacity_per_chip())
        self._owners: Dict[str, int] = {}
        self._per_device: Dict[str, int] = {}
        r = self.registry
        self._g_owner = r.gauge(
            "mingpt_attrib_hbm_bytes",
            help="accounted HBM bytes by owner (shapes/dtypes, exact)",
            labels=("owner",))
        self._g_owner_pd = r.gauge(
            "mingpt_attrib_hbm_per_device_bytes",
            help="accounted HBM bytes by owner on the busiest device "
                 "(total/tp for tp-sharded owners, == total unsharded)",
            labels=("owner",))
        self._g_total = r.gauge(
            "mingpt_attrib_hbm_total_bytes",
            help="sum of accounted HBM bytes across owners")
        self._g_live = r.gauge(
            "mingpt_attrib_hbm_live_bytes",
            help="bytes of all live jax arrays in this process")
        self._g_unattr = r.gauge(
            "mingpt_attrib_hbm_unattributed_bytes",
            help="live bytes no owner accounts for (leak audit residue)")
        self._g_headroom = r.gauge(
            "mingpt_attrib_hbm_headroom_bytes",
            help="chip HBM capacity minus accounted bytes "
                 "(absent off-TPU: no capacity table row)")

    def account(self, owner: str, nbytes: int,
                per_device_bytes: Optional[int] = None) -> None:
        """Declare an owner's bytes. ``per_device_bytes`` is the owner's
        residency on one device (ISSUE 14: total/tp when the owner is
        tp-sharded); omitted it defaults to ``nbytes`` — the single-device
        truth — so existing callers stay exact."""
        if nbytes < 0:
            raise ValueError(f"owner {owner!r}: negative bytes {nbytes}")
        if per_device_bytes is None:
            per_device_bytes = nbytes
        if not (0 <= per_device_bytes <= nbytes):
            raise ValueError(
                f"owner {owner!r}: per_device_bytes {per_device_bytes} "
                f"outside [0, {nbytes}]")
        self._owners[owner] = int(nbytes)
        self._per_device[owner] = int(per_device_bytes)
        self._g_owner.labels(owner=owner).set(int(nbytes))
        self._g_owner_pd.labels(owner=owner).set(int(per_device_bytes))
        total = self.total_bytes()
        self._g_total.set(total)
        if self.capacity_bytes is not None:
            self._g_headroom.set(self.capacity_bytes - total)

    def owners(self) -> Dict[str, int]:
        return dict(sorted(self._owners.items()))

    def per_device(self) -> Dict[str, int]:
        """Per-owner busiest-device bytes, same keys as ``owners()``."""
        return dict(sorted(self._per_device.items()))

    def total_bytes(self) -> int:
        return sum(self._owners.values())

    def live_bytes(self) -> int:
        import jax  # lazy: telemetry must import without a backend

        return sum(int(a.nbytes) for a in jax.live_arrays())

    def audit(self) -> Dict[str, int]:
        """Leak audit: owned vs live bytes. Process-level state (other
        subsystems' arrays count as live), so the report excludes it by
        default — it feeds the gauges and the selftest's leak check."""
        owned = self.total_bytes()
        live = self.live_bytes()
        self._g_live.set(live)
        self._g_unattr.set(max(0, live - owned))
        return {
            "owned_bytes": owned,
            "live_bytes": live,
            "unattributed_bytes": max(0, live - owned),
        }


# ---------------------------------------------------------------------
# mingpt-attrib/1 report
# ---------------------------------------------------------------------


def build_attrib_report(
    programs: ProgramLedger,
    hbm: Optional[HBMLedger] = None,
    include_live: bool = False,
) -> Dict[str, Any]:
    """Assemble the versioned report. ``include_live`` folds the
    ``jax.live_arrays()`` audit in — off by default because live bytes
    are process history, not run state, and would break the
    byte-identical-reports property two sequential runs must have."""
    report: Dict[str, Any] = {
        "schema": ATTRIB_SCHEMA,
        "programs": programs.rows(),
        "peaks": {
            "flops_per_chip": peak_flops_per_chip(),
            "hbm_bandwidth_per_chip": peak_hbm_bytes_per_chip(),
            "hbm_capacity_per_chip": peak_hbm_capacity_per_chip(),
        },
    }
    if hbm is not None:
        owners = hbm.owners()
        total = hbm.total_bytes()
        block: Dict[str, Any] = {
            "owners": owners,
            "per_device_bytes": hbm.per_device(),
            "total_bytes": total,
            "capacity_bytes": hbm.capacity_bytes,
            "headroom_bytes": (None if hbm.capacity_bytes is None
                               else hbm.capacity_bytes - total),
        }
        if include_live:
            block["audit"] = hbm.audit()
        report["hbm"] = block
    return report


_PROGRAM_KEYS = {
    "family": str, "variant": str, "compiles": int, "compile_s": float,
    "flops": float, "bytes_accessed": float, "calls": int,
    "device_s": float, "arith_intensity": float, "expected_mfu": float,
    "measured_mfu": float,
}
_NULLABLE = {"flops", "bytes_accessed", "arith_intensity",
             "expected_mfu", "measured_mfu"}


def validate_attrib_report(report: Dict[str, Any]) -> None:
    """Strict structural validation (raises ValueError). The shape every
    consumer (perf_diff, trace_summary, the /attrib scrape assertions)
    can then rely on without defensive re-checking."""
    if report.get("schema") != ATTRIB_SCHEMA:
        raise ValueError(
            f"not a {ATTRIB_SCHEMA} report: schema={report.get('schema')!r}")
    progs = report.get("programs")
    if not isinstance(progs, list):
        raise ValueError("programs must be a list")
    seen = set()
    for i, row in enumerate(progs):
        if not isinstance(row, dict):
            raise ValueError(f"programs[{i}] is not an object")
        missing = set(_PROGRAM_KEYS) - set(row)
        if missing:
            raise ValueError(f"programs[{i}] missing {sorted(missing)}")
        for key, typ in _PROGRAM_KEYS.items():
            v = row[key]
            if v is None:
                if key in _NULLABLE:
                    continue
                raise ValueError(f"programs[{i}].{key} must not be null")
            if typ is float and isinstance(v, int):
                v = float(v)
            if not isinstance(v, typ) or isinstance(v, bool):
                raise ValueError(
                    f"programs[{i}].{key}={v!r} is not {typ.__name__}")
        if row["compiles"] < 0 or row["calls"] < 0 or row["compile_s"] < 0 \
                or row["device_s"] < 0:
            raise ValueError(f"programs[{i}] has negative accounting")
        key = (row["family"], row["variant"])
        if key in seen:
            raise ValueError(f"duplicate program row {key}")
        seen.add(key)
    hbm = report.get("hbm")
    if hbm is not None:
        owners = hbm.get("owners")
        if not isinstance(owners, dict):
            raise ValueError("hbm.owners must be an object")
        for owner, nb in owners.items():
            if not isinstance(nb, int) or isinstance(nb, bool) or nb < 0:
                raise ValueError(f"hbm.owners[{owner!r}]={nb!r} is not a "
                                 f"non-negative integer")
        if hbm.get("total_bytes") != sum(owners.values()):
            raise ValueError(
                f"hbm.total_bytes={hbm.get('total_bytes')!r} != sum of "
                f"owners {sum(owners.values())}")
        pd = hbm.get("per_device_bytes")
        if pd is not None:
            if not isinstance(pd, dict):
                raise ValueError("hbm.per_device_bytes must be an object")
            if set(pd) != set(owners):
                raise ValueError(
                    f"hbm.per_device_bytes keys {sorted(pd)} != owners "
                    f"{sorted(owners)}")
            for owner, nb in pd.items():
                if not isinstance(nb, int) or isinstance(nb, bool) \
                        or not (0 <= nb <= owners[owner]):
                    raise ValueError(
                        f"hbm.per_device_bytes[{owner!r}]={nb!r} is not an "
                        f"integer in [0, {owners[owner]}]")
    peaks = report.get("peaks")
    if not isinstance(peaks, dict):
        raise ValueError("peaks must be an object")


def dump_attrib_report(report: Dict[str, Any]) -> str:
    """Canonical serialisation: sorted keys, fixed separators — the
    byte-identity contract of the VirtualClock selftest."""
    return json.dumps(report, sort_keys=True, indent=2)


def render_attrib_report(report: Dict[str, Any]) -> str:
    """Human-readable per-family table (stable layout, render_slo_diff
    column idiom)."""

    def _cell(v: Optional[float]) -> str:
        return "n/a" if v is None else f"{v:.4g}"

    lines = [f"Attribution report ({report['schema']}): "
             f"{len(report['programs'])} program rows"]
    lines.append(
        f"  {'family':<16} {'variant':<10} {'flops':>10} {'bytes':>10} "
        f"{'compile_s':>10} {'calls':>7} {'device_s':>10} {'mfu':>8}")
    for row in report["programs"]:
        lines.append(
            f"  {row['family']:<16} {row['variant']:<10} "
            f"{_cell(row['flops']):>10} {_cell(row['bytes_accessed']):>10} "
            f"{row['compile_s']:>10.4g} {row['calls']:>7} "
            f"{row['device_s']:>10.4g} {_cell(row['measured_mfu']):>8}")
    hbm = report.get("hbm")
    if hbm:
        lines.append(f"  HBM: total {hbm['total_bytes']} bytes"
                     + ("" if hbm.get("headroom_bytes") is None else
                        f", headroom {hbm['headroom_bytes']:.3g}"))
        pd = hbm.get("per_device_bytes") or {}
        for owner, nb in hbm["owners"].items():
            per_dev = pd.get(owner)
            suffix = ("" if per_dev is None or per_dev == nb
                      else f"  ({per_dev} / device)")
            lines.append(f"    {owner:<20} {nb:>14}{suffix}")
    return "\n".join(lines)
