"""Roofline peak tables — the single source of truth (ISSUE 5 satellite).

``bench.py`` and ``training/metrics.py`` used to each consult a copy of
these numbers; both now import from here, so a new chip generation is
added in exactly one place. Public numbers throughout.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = [
    "PEAK_FLOPS",
    "PEAK_HBM_BYTES",
    "PEAK_HBM_CAPACITY",
    "peak_flops_per_chip",
    "peak_hbm_bytes_per_chip",
    "peak_hbm_capacity_per_chip",
]

# Peak dense bf16 FLOP/s per chip, for MFU.
# Ordering matters for the longest-prefix lookup below: "TPU v5 lite"
# must precede "TPU v5" so a v5e never reads the v5p row. "v6e"/"v6 lite"
# and "v7"/"v7x" are spelling aliases — PJRT device_kind strings have
# historically used both forms within a generation.
PEAK_FLOPS: Dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,  # v5p (bare "TPU v5" device_kind spelling)
    "TPU v6 lite": 918e12,  # v6e (Trillium)
    "TPU v6e": 918e12,
    "TPU v7x": 2307e12,
    "TPU v7": 2307e12,  # Ironwood: bf16 half of the 4614 TFLOP/s fp8 peak
}

# Peak HBM bandwidth per chip (bytes/s), for memory-bound rooflines
# (KV-cached decode streams the whole parameter set per token, so its
# ceiling is bandwidth, not FLOPs).
PEAK_HBM_BYTES: Dict[str, float] = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,  # v5e
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 2765e9,  # v5p (bare "TPU v5" device_kind spelling)
    "TPU v6 lite": 1640e9,  # v6e (Trillium)
    "TPU v6e": 1640e9,
    "TPU v7x": 7370e9,
    "TPU v7": 7370e9,  # Ironwood
}


# HBM capacity per chip (bytes) — the ceiling the attribution layer's
# headroom gauge reports against (telemetry/attribution.py), distinct
# from the PEAK_HBM_BYTES *bandwidth* table above.
PEAK_HBM_CAPACITY: Dict[str, float] = {
    "TPU v4": 32e9,
    "TPU v5 lite": 16e9,  # v5e
    "TPU v5e": 16e9,
    "TPU v5p": 95e9,
    "TPU v5": 95e9,  # v5p (bare "TPU v5" device_kind spelling)
    "TPU v6 lite": 32e9,  # v6e (Trillium)
    "TPU v6e": 32e9,
    "TPU v7x": 192e9,
    "TPU v7": 192e9,  # Ironwood
}


def _chip_lookup(table: Dict[str, float]) -> Optional[float]:
    # longest-prefix-wins by dict order (see the ordering note above)
    import jax  # lazy: the telemetry package must import without a backend

    kind = jax.devices()[0].device_kind
    for name, val in table.items():
        if kind.startswith(name):
            return val
    return None


def peak_flops_per_chip() -> Optional[float]:
    return _chip_lookup(PEAK_FLOPS)


def peak_hbm_bytes_per_chip() -> Optional[float]:
    return _chip_lookup(PEAK_HBM_BYTES)


def peak_hbm_capacity_per_chip() -> Optional[float]:
    return _chip_lookup(PEAK_HBM_CAPACITY)
