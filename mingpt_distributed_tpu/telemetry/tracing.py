"""Request-scoped tracing (ISSUE 10): one reconstructable timeline per
serving request, across router -> replica -> prefill chunks -> decode
rounds -> retries.

Design constraints, in order:

* **Clock discipline.**  This module never reads a clock.  Every
  timestamp is supplied by the caller from its *injected* clock (the
  fleet's VirtualClock, a replica's SkewedClock, the scheduler's
  ``clock`` callable), which keeps chaos tests sleep-free and makes the
  module trivially GL007-clean.  Consequence: ``ts`` fields are seconds
  in the *caller's clock domain*, not epoch time — durations and
  same-clock deltas are meaningful, absolute wall anchoring is not
  (the flight recorder carries the wall anchor instead).
* **One trace per request.**  The trace_id is the fleet/server
  request_id.  Retry attempts are *spans inside* the same trace
  (``fleet.attempt`` with an ``attempt`` ordinal), never new traces.
  Records arriving for an unknown or already-ended trace are dropped
  and counted (``orphan_records``) — the chaos tests pin that counter
  to zero.
* **Sampling that never hides trouble.**  Errors, sheds, deadline
  expiries and anything retried export unconditionally; happy-path
  traces export with probability ``sample`` decided *deterministically*
  from a hash of the trace_id, so a given request id samples the same
  way in every process and rerun.
* **Bounded.**  Per-trace span/event count and the completed-summary
  deque are capped; overflow increments a per-trace drop counter that
  is exported with the summary, never silently.

Export is ``mingpt-trace/1`` JSONL: ``kind`` is ``span`` | ``event`` |
``request`` (exactly one ``request`` summary per trace).  The strict
loader/validator below is what the chaos selftest and the trace
summarizer both stand on.
"""

import json
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

TRACE_SCHEMA = "mingpt-trace/1"

#: outcomes that count as a successful completion — anything else is
#: trouble and forces export regardless of the sampling probability
HAPPY_OUTCOMES = ("length", "eos")

#: the virtual parent id of root-level spans/events in every trace
ROOT_SPAN_ID = "s0"


@dataclass(frozen=True)
class TraceContext:
    """The propagation token: carried on a Request across the
    router/replica/scheduler boundary.  ``span_id`` is the id new child
    spans and events parent to."""

    trace_id: str
    span_id: str = ROOT_SPAN_ID
    baggage: Dict[str, Any] = field(default_factory=dict)

    def child(self, span_id: str) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.baggage)


def prompt_hash(prompt: Sequence[int], head: int = 16) -> str:
    """Stable 8-hex-digit hash of the prompt head for trace baggage —
    groups shared-prefix traffic without storing token ids."""
    blob = repr([int(t) for t in list(prompt)[:head]]).encode("utf-8")
    return "%08x" % (zlib.crc32(blob) & 0xFFFFFFFF)


def trace_baggage(request: Any) -> Dict[str, Any]:
    """Standard per-request baggage: tenant + prompt-head hash."""
    bag: Dict[str, Any] = {"prompt_hash": prompt_hash(request.prompt)}
    tenant = getattr(request, "tenant", None)
    if tenant:
        bag["tenant"] = tenant
    return bag


class _Trace:
    """Mutable per-trace accumulation state (internal)."""

    __slots__ = ("trace_id", "request_id", "start_s", "baggage", "spans",
                 "events", "open_spans", "forced", "dropped", "_next_id")

    def __init__(self, trace_id: str, request_id: str, start_s: float,
                 baggage: Optional[Dict[str, Any]]):
        self.trace_id = trace_id
        self.request_id = request_id
        self.start_s = float(start_s)
        self.baggage = dict(baggage or {})
        self.spans: List[Dict[str, Any]] = []
        self.events: List[Dict[str, Any]] = []
        self.open_spans: Dict[str, Dict[str, Any]] = {}
        self.forced = False
        self.dropped = 0
        self._next_id = 0

    def new_span_id(self) -> str:
        self._next_id += 1
        return "s%d" % self._next_id

    @property
    def record_count(self) -> int:
        return len(self.spans) + len(self.events) + len(self.open_spans)


class TraceRecorder:
    """Collects per-request spans/events keyed by TraceContext and, at
    ``end_trace``, decides export and appends an exact-duration summary
    to the bounded ``completed`` deque (the SLO engine's input — kept
    for *every* trace, sampled or not).

    All records are mirrored into an attached FlightRecorder ring at
    record time, so crash dumps include recent activity even for traces
    that would not have been sampled.
    """

    def __init__(self, sink=None, sample: float = 1.0,
                 max_spans_per_trace: int = 512,
                 max_completed: int = 1024,
                 registry=None, flight=None):
        if not 0.0 <= float(sample) <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.sink = sink
        self.sample = float(sample)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.flight = flight
        self._active: Dict[str, _Trace] = {}
        self.completed: deque = deque(maxlen=int(max_completed))
        self.orphan_records = 0
        self.exported_traces = 0
        self.unsampled_traces = 0
        self._c_exported = self._c_dropped = self._c_orphans = None
        if registry is not None:
            self._c_exported = registry.counter(
                "mingpt_trace_exported_total",
                help="request traces exported to the JSONL sink",
                labels=("cause",))
            self._c_dropped = registry.counter(
                "mingpt_trace_unsampled_total",
                help="happy-path request traces dropped by sampling")
            self._c_orphans = registry.counter(
                "mingpt_trace_orphan_records_total",
                help="span/event records for unknown or ended traces "
                     "(dropped)")

    # -- lifecycle ----------------------------------------------------

    def start_trace(self, request_id: str, now: float,
                    baggage: Optional[Dict[str, Any]] = None,
                    ) -> TraceContext:
        if request_id in self._active:
            raise ValueError(f"trace {request_id!r} already active")
        tr = _Trace(request_id, request_id, now, baggage)
        self._active[request_id] = tr
        return TraceContext(request_id, ROOT_SPAN_ID, tr.baggage)

    def mark_forced(self, ctx: TraceContext) -> None:
        tr = self._active.get(ctx.trace_id)
        if tr is not None:
            tr.forced = True

    # -- recording ----------------------------------------------------

    def _lookup(self, ctx: TraceContext) -> Optional[_Trace]:
        tr = self._active.get(ctx.trace_id)
        if tr is None:
            self.orphan_records += 1
            if self._c_orphans is not None:
                self._c_orphans.inc()
        return tr

    def add_span(self, ctx: TraceContext, name: str, ts: float,
                 dur_s: float, **attrs) -> None:
        """Record a completed span parented to ``ctx``."""
        tr = self._lookup(ctx)
        if tr is None:
            return
        if tr.record_count >= self.max_spans_per_trace:
            tr.dropped += 1
            return
        rec = {"trace_id": tr.trace_id, "span_id": tr.new_span_id(),
               "parent_id": ctx.span_id, "name": name, "ts": float(ts),
               "dur_s": max(0.0, float(dur_s))}
        rec.update(attrs)
        tr.spans.append(rec)
        self._mirror("span", rec)

    def open_span(self, ctx: TraceContext, name: str, now: float,
                  **attrs) -> TraceContext:
        """Open a span and return the child context that parents work
        done inside it (the router's per-attempt span rides on the
        attempt Request this way).  Open spans don't count against the
        cap — they are bounded by in-flight attempts."""
        tr = self._lookup(ctx)
        if tr is None:
            return ctx
        sid = tr.new_span_id()
        tr.open_spans[sid] = {
            "trace_id": tr.trace_id, "span_id": sid,
            "parent_id": ctx.span_id, "name": name, "ts": float(now),
            **attrs}
        return ctx.child(sid)

    def close_span(self, ctx: TraceContext, now: float, **attrs) -> None:
        tr = self._lookup(ctx)
        if tr is None:
            return
        rec = tr.open_spans.pop(ctx.span_id, None)
        if rec is None:
            self.orphan_records += 1
            if self._c_orphans is not None:
                self._c_orphans.inc()
            return
        rec["dur_s"] = max(0.0, float(now) - rec["ts"])
        rec.update(attrs)
        tr.spans.append(rec)
        self._mirror("span", rec)

    def cancel_span(self, ctx: TraceContext) -> None:
        """Drop an open span without recording it (e.g. an attempt that
        never counted because the replica queue was full)."""
        tr = self._active.get(ctx.trace_id)
        if tr is not None:
            tr.open_spans.pop(ctx.span_id, None)

    def add_event(self, ctx: TraceContext, name: str, now: float,
                  **attrs) -> None:
        tr = self._lookup(ctx)
        if tr is None:
            return
        if tr.record_count >= self.max_spans_per_trace:
            tr.dropped += 1
            return
        rec = {"trace_id": tr.trace_id, "parent_id": ctx.span_id,
               "name": name, "ts": float(now)}
        rec.update(attrs)
        tr.events.append(rec)
        self._mirror("event", rec)

    # -- completion ---------------------------------------------------

    def end_trace(self, ctx: TraceContext, now: float, outcome: str,
                  n_tokens: int = 0, attempts: int = 1,
                  **attrs) -> Optional[Dict[str, Any]]:
        """Close the trace, compute the exact-duration summary, decide
        export, and return the summary (None for an orphan end)."""
        tr = self._active.pop(ctx.trace_id, None)
        if tr is None:
            self.orphan_records += 1
            if self._c_orphans is not None:
                self._c_orphans.inc()
            return None
        for rec in tr.open_spans.values():
            rec["dur_s"] = max(0.0, float(now) - rec["ts"])
            rec["unclosed"] = True
            tr.spans.append(rec)
            self._mirror("span", rec)
        tr.open_spans.clear()

        emit_ts = sorted(e["ts"] for e in tr.events if e["name"] == "emit")
        gaps = [b - a for a, b in zip(emit_ts, emit_ts[1:])]
        ttft = (emit_ts[0] - tr.start_s) if emit_ts else None
        retried = int(attempts) > 1
        forced = tr.forced or retried or outcome not in HAPPY_OUTCOMES
        sampled = forced or self._sample_hit(tr.trace_id)

        summary: Dict[str, Any] = {
            "trace_id": tr.trace_id, "request_id": tr.request_id,
            "ts": tr.start_s, "end_ts": float(now),
            "total_s": max(0.0, float(now) - tr.start_s),
            "outcome": outcome, "n_tokens": int(n_tokens),
            "attempts": int(attempts), "retried": retried,
            "ttft_s": ttft,
            "itl_s": gaps,
            "itl_mean_s": (sum(gaps) / len(gaps)) if gaps else None,
            "n_spans": len(tr.spans), "n_events": len(tr.events),
            "dropped_records": tr.dropped,
            "baggage": tr.baggage,
            "sampled": sampled,
            "sample_cause": ("forced" if forced else "probability")
                            if sampled else None,
        }
        summary.update(attrs)
        self.completed.append(summary)
        self._mirror("request", summary)

        if sampled:
            self.exported_traces += 1
            if self._c_exported is not None:
                self._c_exported.labels(
                    cause=summary["sample_cause"]).inc()
            if self.sink is not None:
                for rec in tr.spans:
                    self.sink.write("span", rec)
                for rec in tr.events:
                    self.sink.write("event", rec)
                self.sink.write("request", summary)
        else:
            self.unsampled_traces += 1
            if self._c_dropped is not None:
                self._c_dropped.inc()
        return summary

    def completed_requests(self) -> List[Dict[str, Any]]:
        """Every finished trace's summary (sampled or not) — the SLO
        engine's input."""
        return list(self.completed)

    @property
    def active_traces(self) -> int:
        return len(self._active)

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()

    # -- internals ----------------------------------------------------

    def _sample_hit(self, trace_id: str) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = zlib.crc32(trace_id.encode("utf-8")) & 0xFFFFFFFF
        return (h % 10_000) < int(self.sample * 10_000)

    def _mirror(self, kind: str, rec: Dict[str, Any]) -> None:
        if self.flight is not None:
            self.flight.record(kind, rec)


def trace_sink(path: str):
    """A JsonlEventSink stamped with the mingpt-trace/1 schema."""
    from .export import JsonlEventSink
    return JsonlEventSink(path, schema=TRACE_SCHEMA)


# ---------------------------------------------------------------------
# strict mingpt-trace/1 loading + validation
# ---------------------------------------------------------------------

_KINDS = ("span", "event", "request")


def _fail(where: str, msg: str) -> None:
    raise ValueError(f"mingpt-trace/1 validation: {where}: {msg}")


def _check_num(where: str, rec: Dict[str, Any], key: str,
               minimum: float = 0.0) -> float:
    v = rec.get(key)
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        _fail(where, f"{key!r} must be a number, got {v!r}")
    if v < minimum:
        _fail(where, f"{key!r} must be >= {minimum}, got {v!r}")
    return float(v)


def validate_trace_records(records: Sequence[Dict[str, Any]],
                           ) -> Dict[str, Dict[str, Any]]:
    """Strictly validate a decoded mingpt-trace/1 record stream and
    group it per trace.  Raises ValueError on the first violation.

    Enforced invariants (the chaos-selftest acceptance bar):

    * schema/kind/trace_id well-formed on every record;
    * exactly one ``request`` summary per trace_id;
    * zero orphans: every span/event parents to ``s0`` or to a span id
      present in the same trace;
    * durations non-negative, ``total_s`` coherent with start/end;
    * emit-event count equals the summary's ``n_tokens``, and the
    * summary's ``ttft_s``/``itl_mean_s`` reproduce exactly from the
      emit-event timestamps (same clock by construction).

    Cross-clock containment (a skewed replica's span falling inside the
    fleet-clock [start, end] window) is deliberately NOT asserted —
    clock skew is a feature of the chaos fleet, not a trace bug.
    """
    traces: Dict[str, Dict[str, Any]] = {}
    for i, rec in enumerate(records):
        where = f"record {i}"
        if not isinstance(rec, dict):
            _fail(where, f"not an object: {rec!r}")
        if rec.get("schema") != TRACE_SCHEMA:
            _fail(where, f"schema {rec.get('schema')!r} != {TRACE_SCHEMA!r}")
        kind = rec.get("kind")
        if kind not in _KINDS:
            _fail(where, f"kind {kind!r} not in {_KINDS}")
        tid = rec.get("trace_id")
        if not isinstance(tid, str) or not tid:
            _fail(where, f"trace_id {tid!r} must be a non-empty string")
        _check_num(where, rec, "ts")
        tr = traces.setdefault(
            tid, {"request": None, "spans": [], "events": []})
        if kind == "span":
            for key in ("span_id", "parent_id", "name"):
                if not isinstance(rec.get(key), str) or not rec[key]:
                    _fail(where, f"span {key!r} missing or empty")
            _check_num(where, rec, "dur_s")
            tr["spans"].append(rec)
        elif kind == "event":
            if not isinstance(rec.get("parent_id"), str):
                _fail(where, "event parent_id missing")
            if not isinstance(rec.get("name"), str) or not rec["name"]:
                _fail(where, "event name missing or empty")
            tr["events"].append(rec)
        else:
            if tr["request"] is not None:
                _fail(where, f"duplicate request summary for trace {tid!r}")
            if not isinstance(rec.get("outcome"), str) or not rec["outcome"]:
                _fail(where, "request outcome missing")
            for key in ("end_ts", "total_s"):
                _check_num(where, rec, key)
            for key in ("n_tokens", "attempts"):
                v = rec.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    _fail(where, f"request {key!r} must be an int >= 0")
            tr["request"] = rec

    for tid, tr in traces.items():
        where = f"trace {tid!r}"
        req = tr["request"]
        if req is None:
            _fail(where, "no request summary record")
        span_ids = {s["span_id"] for s in tr["spans"]}
        if len(span_ids) != len(tr["spans"]):
            _fail(where, "duplicate span ids")
        valid_parents = span_ids | {ROOT_SPAN_ID}
        for s in tr["spans"]:
            if s["parent_id"] not in valid_parents:
                _fail(where, f"orphan span {s['span_id']!r} "
                             f"(parent {s['parent_id']!r} unknown)")
        for e in tr["events"]:
            if e["parent_id"] not in valid_parents:
                _fail(where, f"orphan event {e['name']!r} "
                             f"(parent {e['parent_id']!r} unknown)")
        if abs((req["end_ts"] - req["ts"]) - req["total_s"]) > 1e-6:
            _fail(where, "total_s does not match end_ts - ts")
        emit_ts = sorted(e["ts"] for e in tr["events"]
                         if e["name"] == "emit")
        if len(emit_ts) != req["n_tokens"]:
            _fail(where, f"{len(emit_ts)} emit events != "
                         f"n_tokens {req['n_tokens']}")
        if emit_ts:
            ttft = emit_ts[0] - req["ts"]
            if req.get("ttft_s") is None or \
                    abs(req["ttft_s"] - ttft) > 1e-6:
                _fail(where, f"ttft_s {req.get('ttft_s')!r} does not "
                             f"reproduce from emit events ({ttft})")
            gaps = [b - a for a, b in zip(emit_ts, emit_ts[1:])]
            if gaps:
                mean = sum(gaps) / len(gaps)
                if req.get("itl_mean_s") is None or \
                        abs(req["itl_mean_s"] - mean) > 1e-6:
                    _fail(where, "itl_mean_s does not reproduce from "
                                 "emit events")
    return traces


def load_trace_jsonl(path: str) -> Dict[str, Dict[str, Any]]:
    """Read + strictly validate a mingpt-trace/1 JSONL file; returns
    ``{trace_id: {"request": rec, "spans": [...], "events": [...]}}``."""
    records = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not valid JSON: {e}") from e
    return validate_trace_records(records)
