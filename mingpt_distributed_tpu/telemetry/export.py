"""Telemetry exporters (ISSUE 5 tentpole, part 3).

Two export surfaces over the one :class:`MetricsRegistry`:

* **Prometheus text exposition** (``render_prometheus``) + ``/healthz``,
  served from a stdlib :class:`ThreadingHTTPServer`
  (:class:`TelemetryServer`) behind ``serve.py --metrics-port`` and
  ``trainer_config.metrics_port`` — pull-based, zero third-party deps.
  ``parse_prometheus`` is the strict counterpart the tests and the
  selftest self-scrape use: every non-comment line must match the
  exposition grammar (no string-contains assertions).

* **Versioned JSONL events** (:class:`JsonlEventSink`): one schema for
  what used to be two ad-hoc shapes — the trainer's per-step
  ``metrics_jsonl`` records and the serving summary JSON. Every line is
  ``{"schema": SCHEMA_VERSION, "kind": <kind>, "ts": <epoch s>, ...}``
  with the producer's payload flat at the top level, so pre-existing
  consumers reading ``rec["loss"]``/``rec["step"]`` keep working and new
  consumers can route on ``kind`` (``train_step`` | ``serving_summary``
  | ``span`` | ``event``). See docs/RELEASE_NOTES.md for migration.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, TextIO, Tuple

from mingpt_distributed_tpu.telemetry.registry import MetricsRegistry

__all__ = [
    "SCHEMA_VERSION",
    "JsonlEventSink",
    "TelemetryServer",
    "merge_fleet_pages",
    "parse_prometheus",
    "register_build_info",
    "render_fleet_prometheus",
    "render_prometheus",
]

#: Version tag stamped on every JSONL line; bump on breaking layout
#: changes and document the migration in docs/RELEASE_NOTES.md.
SCHEMA_VERSION = "mingpt-telemetry/1"


class JsonlEventSink:
    """Append-only, versioned JSONL event stream (thread-safe)."""

    def __init__(self, path: Optional[str] = None, file: Optional[TextIO] = None,
                 schema: str = SCHEMA_VERSION):
        if (path is None) == (file is None):
            raise ValueError("give exactly one of path / file")
        self._file = file if file is not None else open(path, "a")
        self._lock = threading.Lock()
        #: per-sink schema tag — the trace recorder reuses this sink
        #: with "mingpt-trace/1" (payloads always carry their own ts)
        self.schema = schema

    def write(self, kind: str, data: Dict[str, Any]) -> None:
        rec = {"schema": self.schema, "kind": kind}
        rec.setdefault("ts", data.get("ts", time.time()))
        rec.update(data)
        line = json.dumps(rec) + "\n"
        with self._lock:
            self._file.write(line)
            self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None  # type: ignore[assignment]


# ---------------------------------------------------------------------------
# Prometheus text exposition (format version 0.0.4)
# ---------------------------------------------------------------------------


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return (
        s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()
        )
        return f"{name}{{{body}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def render_prometheus(registry: MetricsRegistry) -> str:
    """The registry as Prometheus text exposition. Families with no
    children yet still emit HELP/TYPE lines, so a scrape can assert a
    labeled counter (e.g. the recompile watchdog's) is absent-thus-zero
    without special-casing."""
    out: List[str] = []
    for fam in registry.collect():
        if fam.help:
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.children():
            if fam.kind == "histogram":
                for upper, cum in child.cumulative():
                    le = "+Inf" if upper == float("inf") else _fmt(upper)
                    out.append(
                        _sample(fam.name + "_bucket",
                                {**labels, "le": le}, cum)
                    )
                out.append(_sample(fam.name + "_sum", labels, child.sum))
                out.append(_sample(fam.name + "_count", labels, child.count))
            else:
                out.append(_sample(fam.name, labels, child.value))
    return "\n".join(out) + "\n"


def render_fleet_prometheus(
    base_registry: Optional[MetricsRegistry],
    replica_registries: Dict[str, MetricsRegistry],
) -> str:
    """Fleet-wide merged exposition (ISSUE 13): the union of N
    per-replica registries under an injected ``replica`` label, plus an
    optional base registry (router/supervisor-level families) emitted
    unlabeled — one scrape covers the whole fleet.

    Families sharing a name across replicas merge under ONE HELP/TYPE
    header (the strict parser rejects duplicate TYPE lines, so the merge
    must not naively concatenate pages); a name registered with two
    different instrument kinds anywhere in the fleet raises — the same
    contract ``MetricsRegistry`` enforces within one process. Output
    order is sorted family names then sorted replica names:
    byte-deterministic for identical registry states."""
    sources: List[Tuple[Optional[str], MetricsRegistry]] = []
    if base_registry is not None:
        sources.append((None, base_registry))
    sources.extend(sorted(replica_registries.items()))
    fams: Dict[str, List[Tuple[Optional[str], Any]]] = {}
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for replica, reg in sources:
        for fam in reg.collect():
            prev = kinds.get(fam.name)
            if prev is not None and prev != fam.kind:
                raise ValueError(
                    f"fleet merge: family {fam.name!r} is {fam.kind} on "
                    f"{replica or 'base'} but {prev} elsewhere — exposition "
                    f"would be incoherent")
            kinds[fam.name] = fam.kind
            if fam.help and fam.name not in helps:
                helps[fam.name] = fam.help
            fams.setdefault(fam.name, []).append((replica, fam))
    out: List[str] = []
    for name in sorted(fams):
        if helps.get(name):
            out.append(f"# HELP {name} {_escape_help(helps[name])}")
        out.append(f"# TYPE {name} {kinds[name]}")
        for replica, fam in fams[name]:
            for labels, child in fam.children():
                if replica is not None:
                    labels = {"replica": replica, **labels}
                if fam.kind == "histogram":
                    for upper, cum in child.cumulative():
                        le = "+Inf" if upper == float("inf") else _fmt(upper)
                        out.append(_sample(
                            name + "_bucket", {**labels, "le": le}, cum))
                    out.append(_sample(name + "_sum", labels, child.sum))
                    out.append(_sample(name + "_count", labels, child.count))
                else:
                    out.append(_sample(name, labels, child.value))
    return "\n".join(out) + "\n"


_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary|untyped)$"
)
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="     # labels: name=
    r'"(?:[^"\\\n]|\\["\\n])*"'             # "value" with \" \\ \n escapes
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*="
    r'"(?:[^"\\\n]|\\["\\n])*")*)?)\})?'
    r" (NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)"  # value
    r"(?: [0-9]+)?$"                        # optional timestamp
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"'
)


def _unescape_label(s: str) -> str:
    # single pass, not chained str.replace: replacing "\n" first would
    # corrupt a literal backslash-then-n ("\\" + "n" must stay "\" + "n")
    out: List[str] = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(s[i])
        i += 1
    return "".join(out)


def _parse_value(s: str) -> float:
    if s == "NaN":
        return float("nan")
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Strict exposition parser: every non-blank, non-comment line must
    match the sample grammar exactly, histogram families must expose
    coherent ``_bucket``/``_sum``/``_count`` triplets (cumulative,
    ``+Inf`` bucket == ``_count``). Raises ``ValueError`` on any
    violation. Returns ``{"types": {family: kind}, "samples":
    [(name, labels, value)]}``.
    """
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                name, kind = m.groups()
                if name in types:
                    raise ValueError(f"line {lineno}: duplicate TYPE {name}")
                types[name] = kind
                continue
            if line.startswith("# TYPE"):
                # a TYPE line that failed the grammar must not pass as a
                # free-form comment — that's exactly the class of drift a
                # strict parser exists to catch
                raise ValueError(f"line {lineno}: malformed TYPE {line!r}")
            if _HELP_RE.match(line) or line.startswith("# "):
                continue
            raise ValueError(f"line {lineno}: malformed comment {line!r}")
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, labelblob, value = m.group(1), m.group(2), m.group(3)
        labels = {
            k: _unescape_label(v)
            for k, v in _LABEL_PAIR_RE.findall(labelblob or "")
        }
        samples.append((name, labels, _parse_value(value)))

    # histogram triplet coherence
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        series: Dict[Tuple[Tuple[str, str], ...], Dict[str, Any]] = {}
        for name, labels, value in samples:
            base = {k: v for k, v in labels.items() if k != "le"}
            key = tuple(sorted(base.items()))
            rec = series.setdefault(key, {"buckets": [], "sum": None,
                                          "count": None})
            if name == fam + "_bucket":
                if "le" not in labels:
                    raise ValueError(f"{fam}_bucket sample without le label")
                rec["buckets"].append((_parse_value(labels["le"]), value))
            elif name == fam + "_sum":
                rec["sum"] = value
            elif name == fam + "_count":
                rec["count"] = value
        series = {k: v for k, v in series.items()
                  if v["buckets"] or v["sum"] is not None
                  or v["count"] is not None}
        for key, rec in series.items():
            if not rec["buckets"] or rec["sum"] is None or rec["count"] is None:
                raise ValueError(
                    f"histogram {fam}{dict(key)} missing one of "
                    f"_bucket/_sum/_count"
                )
            bounds = [b for b, _ in rec["buckets"]]
            counts = [c for _, c in rec["buckets"]]
            if bounds != sorted(bounds) or bounds[-1] != float("inf"):
                raise ValueError(
                    f"histogram {fam}: le bounds not increasing to +Inf")
            if counts != sorted(counts):
                raise ValueError(
                    f"histogram {fam}: bucket counts not cumulative")
            if counts[-1] != rec["count"]:
                raise ValueError(
                    f"histogram {fam}: +Inf bucket {counts[-1]} != _count "
                    f"{rec['count']}"
                )
    return {"types": types, "samples": samples}


def merge_fleet_pages(
    base_page: Optional[str],
    replica_pages: Dict[str, str],
    label: str = "replica",
) -> str:
    """Fleet merge over ALREADY-RENDERED exposition pages (ISSUE 16).

    :func:`render_fleet_prometheus` merges live ``MetricsRegistry``
    objects — which only works while every replica shares the router's
    process. A process-isolated fleet has nothing but each replica's
    ``/metrics`` TEXT as fetched over its RPC socket; this merges those
    pages under the same contract: ONE HELP/TYPE header per family (the
    strict parser rejects duplicate TYPE lines, so naive concatenation
    is not an option), a ``replica`` label injected on every replica
    sample, a kind conflict anywhere in the fleet raises, and output is
    sorted (families, then base-before-replicas in sorted replica order)
    so identical inputs render byte-identically. Every input page is
    strict-parsed first — a replica shipping a malformed page fails the
    merge loudly instead of corrupting the fleet scrape.

    ``label`` renames the injected label: the cross-host fleet (ISSUE
    19) merges per-host pages — which already carry ``replica`` labels —
    under ``label="host"``, so a two-level scrape stays coherent."""
    sources: List[Tuple[Optional[str], str]] = []
    if base_page is not None:
        sources.append((None, base_page))
    sources.extend(sorted(replica_pages.items()))
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    fam_samples: Dict[str, List[Tuple[Optional[str], str,
                                      Dict[str, str], float]]] = {}
    for replica, page in sources:
        parsed = parse_prometheus(page)
        for fam, kind in parsed["types"].items():
            prev = kinds.get(fam)
            if prev is not None and prev != kind:
                raise ValueError(
                    f"fleet page merge: family {fam!r} is {kind} on "
                    f"{replica or 'base'} but {prev} elsewhere — "
                    f"exposition would be incoherent")
            kinds[fam] = kind
            fam_samples.setdefault(fam, [])
        for line in page.splitlines():
            m = _HELP_RE.match(line)
            if m and m.group(1) not in helps:
                helps[m.group(1)] = m.group(2)
        for name, labels, value in parsed["samples"]:
            fam = name
            if fam not in parsed["types"]:
                for suffix in ("_bucket", "_sum", "_count"):
                    stem = name[: -len(suffix)]
                    if name.endswith(suffix) and stem in parsed["types"]:
                        fam = stem
                        break
            if fam not in parsed["types"]:
                raise ValueError(
                    f"fleet page merge: sample {name!r} on "
                    f"{replica or 'base'} has no TYPE header")
            fam_samples[fam].append((replica, name, labels, value))
    out: List[str] = []
    for fam in sorted(kinds):
        if helps.get(fam):
            # help text comes off the wire already escaped — verbatim
            out.append(f"# HELP {fam} {helps[fam]}")
        out.append(f"# TYPE {fam} {kinds[fam]}")
        for replica, name, labels, value in fam_samples[fam]:
            if replica is not None:
                labels = {label: replica, **labels}
            out.append(_sample(name, labels, value))
    return "\n".join(out) + "\n"


def register_build_info(registry: MetricsRegistry):
    """The Prometheus build-info idiom (ISSUE 10): a constant-1 gauge
    whose labels carry the package and jax/jaxlib versions, so a scrape
    can answer "what exactly is this replica running".  Version lookup
    never initializes a JAX backend (``__version__`` only) and degrades
    to ``unavailable`` when the library is absent."""
    from mingpt_distributed_tpu import __version__

    try:
        import jax
        jax_version = jax.__version__
    except Exception:
        jax_version = "unavailable"
    try:
        import jaxlib
        jaxlib_version = jaxlib.__version__
    except Exception:
        jaxlib_version = "unavailable"
    g = registry.gauge(
        "mingpt_build_info",
        help="constant 1; labels carry package/jax/jaxlib versions",
        labels=("version", "jax", "jaxlib"))
    g.labels(version=__version__, jax=jax_version,
             jaxlib=jaxlib_version).set(1)
    return g


# ---------------------------------------------------------------------------
# Pull endpoint: /metrics + /healthz + /debug/flight on a stdlib server
# ---------------------------------------------------------------------------


class TelemetryServer:
    """``/metrics`` (Prometheus text), ``/healthz`` (JSON liveness +
    fleet health) and ``/debug/flight`` (on-demand flight-recorder
    snapshot) on a daemon-threaded stdlib server. ``port=0`` binds an
    ephemeral port (exposed as ``.port``) — what the CI smoke uses so
    parallel runs never collide.

    ``health_provider`` / ``flight_provider`` / ``attrib_provider`` /
    ``metrics_provider`` are settable attributes (read per request, so
    they can be wired after backend construction): ``health_provider``
    returns a dict merged into the healthz document — serve.py wires
    ``Router.health_report`` so /healthz carries per-replica breaker
    state and health-gate reasons (ISSUE 10) — ``flight_provider``
    returns a flight snapshot document (without one ``/debug/flight``
    is 404), ``attrib_provider`` returns the mingpt-attrib/1 (or
    fleet-wrapped) performance-attribution report served as JSON on
    ``/attrib`` (404 without one — ISSUE 13), and ``metrics_provider``
    overrides the ``/metrics`` body — the fleet router installs
    ``render_fleet_prometheus`` over the per-replica registries here so
    one scrape covers every replica under a ``replica`` label."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "127.0.0.1",
        health_provider=None,
        flight_provider=None,
        attrib_provider=None,
        metrics_provider=None,
    ):
        self.registry = registry
        self.health_provider = health_provider
        self.flight_provider = flight_provider
        self.attrib_provider = attrib_provider
        self.metrics_provider = metrics_provider
        self._t0 = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    mp = outer.metrics_provider
                    page = (render_prometheus(outer.registry)
                            if mp is None else mp())
                    body = page.encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/attrib":
                    ap = outer.attrib_provider
                    if ap is None:
                        self.send_error(
                            404, "no attribution ledger configured")
                        return
                    try:
                        doc = ap()
                    except Exception as e:
                        doc = {"error": repr(e)}
                    body = json.dumps(doc, sort_keys=True).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    doc = {
                        "status": "ok",
                        "uptime_s": round(time.time() - outer._t0, 3),
                    }
                    hp = outer.health_provider
                    if hp is not None:
                        try:
                            doc.update(hp())
                        except Exception as e:  # liveness must survive
                            doc["status"] = "error"
                            doc["health_provider_error"] = repr(e)
                    body = json.dumps(doc).encode()
                    ctype = "application/json"
                elif path == "/debug/flight":
                    fp = outer.flight_provider
                    if fp is None:
                        self.send_error(
                            404, "no flight recorder configured")
                        return
                    try:
                        snap = fp()
                    except Exception as e:
                        snap = {"error": repr(e)}
                    body = json.dumps(snap, default=repr).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "unknown path (try /metrics)")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # quiet: scrapes are noise
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-http",
            daemon=True,
        )
        self._thread.start()

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
