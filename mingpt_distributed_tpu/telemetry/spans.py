"""Low-overhead span tracer (ISSUE 5 tentpole, part 1).

Nested wall-time spans on the monotonic clock, recorded into a bounded
ring buffer (a deque with ``maxlen`` — a stuck exporter can never grow
host memory) and optionally streamed to the versioned JSONL sink. The
trainer wraps its step/snapshot/eval phases; the serving scheduler wraps
admission → prefill-chunk → decode-round. ``tools/trace_summary.py``
accepts the span JSONL as an alternate input alongside profiler traces.

Overhead discipline: a disabled tracer returns one shared no-op context
manager (no allocation per call), and an enabled span costs two clock
reads, one dict build and a deque append — no locks on the hot path
beyond the deque's internal one. Multi-process runs gate the *default*
tracer to process 0 (``telemetry.get_tracer()``), the same single-writer
convention as MetricsLogger.

Record layout (also the JSONL ``kind: "span"`` payload):
``{"name", "ts" (epoch s, start), "dur_s", "depth", <attrs...>}``.
Point events (``tracer.event``) carry ``{"name", "ts", <attrs...>}``.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from mingpt_distributed_tpu.telemetry.export import JsonlEventSink

__all__ = ["SpanTracer", "log_event", "process_index"]


def process_index() -> int:
    """jax.process_index() when a backend is up, else 0 — telemetry must
    never be the thing that initialises (or crashes on) a backend."""
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "_t0", "_ts")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        self._ts = time.time()
        self._tracer._depth_tls.depth = getattr(
            self._tracer._depth_tls, "depth", 0) + 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        depth = getattr(self._tracer._depth_tls, "depth", 1) - 1
        self._tracer._depth_tls.depth = depth
        rec = {"name": self.name, "ts": self._ts,
               "dur_s": dur, "depth": depth}
        if self.attrs:
            rec.update(self.attrs)
        self._tracer._record("span", rec)
        return False


class SpanTracer:
    """Nested spans + point events in a bounded ring, optional JSONL."""

    def __init__(
        self,
        capacity: int = 4096,
        sink: Optional[JsonlEventSink] = None,
        enabled: bool = True,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.capacity = capacity
        self.sink = sink
        self.emitted = 0  # total ever recorded; ring keeps the newest
        self._ring: deque = deque(maxlen=capacity)
        self._depth_tls = threading.local()

    def span(self, name: str, **attrs: Any):
        """Context manager timing a nested phase. Near-free when the
        tracer is disabled (one shared no-op object)."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, attrs)

    def event(self, name: str, **attrs: Any) -> None:
        """A point-in-time event (no duration) — watchdog firings, log
        lines, phase markers."""
        if not self.enabled:
            return
        rec = {"name": name, "ts": time.time(),
               "depth": getattr(self._depth_tls, "depth", 0)}
        rec.update(attrs)
        self._record("event", rec)

    def _record(self, kind: str, rec: Dict[str, Any]) -> None:
        rec["kind"] = kind
        self._ring.append(rec)
        self.emitted += 1
        if self.sink is not None:
            payload = dict(rec)
            payload.pop("kind")
            self.sink.write(kind, payload)

    @property
    def dropped(self) -> int:
        return self.emitted - len(self._ring)

    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    def attach_jsonl(self, path: str) -> None:
        """Start streaming spans/events to a JSONL file (idempotent for
        the same tracer: replaces any previous sink)."""
        if self.sink is not None:
            self.sink.close()
        self.sink = JsonlEventSink(path)

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()
            self.sink = None


def log_event(
    message: str,
    *,
    tracer: Optional[SpanTracer] = None,
    file=None,
    **attrs: Any,
) -> None:
    """Replacement for bare ``print()`` in multi-process code paths: the
    line is prefixed with the process index (so interleaved pod output
    stays attributable) and mirrored into the tracer's event ring/JSONL.
    Callers keep their own process-0 gating where they want single-writer
    output; this helper makes whatever IS printed attributable.
    """
    print(f"[p{process_index()}] {message}", file=file or sys.stdout,
          flush=True)
    t = tracer
    if t is None:
        from mingpt_distributed_tpu import telemetry

        t = telemetry.get_tracer()
    t.event("log", message=message, **attrs)
