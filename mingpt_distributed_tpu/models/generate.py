"""Autoregressive generation — KV-cached, fully compiled.

Same contract as the reference's GPT.generate
(/root/reference/mingpt/model.py:322-356): greedy or sampled decoding with
``temperature`` and optional ``top_k``, context bounded by ``block_size``.

The mechanism is deliberately NOT the reference's: the reference re-runs the
full forward over the whole (cropped) sequence for every new token with a
growing ``torch.cat`` — O(T·full-forward), shape-changing every step, which
under jit would recompile per step (SURVEY §3.3 flags this as the idiom not
to translate). Here decoding is two compiled programs:

  1. **prefill** — one batched forward over the prompt that also writes every
     layer's K/V into a preallocated ``(L, B, block_size, KV, hd)`` cache;
  2. **decode** — a single ``lax.scan`` over ``max_new_tokens`` steps, each
     step one-token attention against the cache (static shapes throughout,
     cache updated in place via dynamic_update_slice).

Context-window semantics match the reference exactly: generation is
**unbounded** — when prompt+generation no longer fit ``block_size``, decoding
switches to a sliding-window program that re-crops to the last ``block_size``
tokens every step (/root/reference/mingpt/model.py:336-337). The window slide
re-positions every token (learned absolute positions shift), so cached K/V
written at the old positions would be stale — the sliding program therefore
re-forwards the full (static-shape) window per step, exactly the reference's
O(T·forward) semantics, still as one compiled ``lax.scan``. The KV-cached
fast path handles the common fits-the-window case.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import gpt
from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.ops import layers as L

Cache = Dict[str, jax.Array]  # {"k","v"}: (n_layer, B, block_size, KV, hd)


def init_cache(cfg: GPTConfig, batch: int, dtype=None) -> Cache:
    shape = (cfg.n_layer, batch, cfg.block_size, cfg.kv_heads, cfg.head_dim)
    dtype = dtype or jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_block(
    x: jax.Array,            # (B, T, D) — T = prompt length or 1
    blk: gpt.Params,         # one layer's params (no leading L axis)
    cache: Cache,            # FULL (L, B, S, KV, hd) buffers, updated here
    layer: int,
    offset: jax.Array,       # scalar: absolute position of x[:, 0]
    cfg: GPTConfig,
) -> Tuple[jax.Array, Cache]:
    """One pre-LN block; writes this call's (B, T, KV, hd) k/v into the
    full cache at (layer, :, offset) and attends against the layer's
    slice. Returns (y, cache).

    The update is a small dynamic_update_slice on the big buffer — XLA
    aliases it in place through the unrolled layer chain and the decode
    scan carry. The original layer ``lax.scan`` instead emitted every
    layer's updated cache as stacked ys, rewriting the ENTIRE cache every
    decode step — one-token decode scaled with cache size (~5.6 ms/token
    at gpt2-124M b8, the r4/r5 decode mystery) instead of with the
    one-slot update.
    """
    b, t, _ = x.shape
    nh, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    h = gpt._norm(x, blk["ln1_scale"], blk.get("ln1_bias"), cfg)
    q = L.dense(h, blk["wq"], blk.get("bq")).reshape(b, t, nh, hd)
    k = L.dense(h, blk["wk"], blk.get("bk")).reshape(b, t, kv, hd)
    v = L.dense(h, blk["wv"], blk.get("bv")).reshape(b, t, kv, hd)
    if cfg.rope:
        cos, sin = attn_ops.rope_tables(
            offset + jnp.arange(t), hd, cfg.rope_theta
        )
        q = attn_ops.apply_rope(q, cos, sin)
        k = attn_ops.apply_rope(k, cos, sin)

    big_k = jax.lax.dynamic_update_slice(
        cache["k"], k.astype(cache["k"].dtype)[None],
        (layer, 0, offset, 0, 0))
    big_v = jax.lax.dynamic_update_slice(
        cache["v"], v.astype(cache["v"].dtype)[None],
        (layer, 0, offset, 0, 0))
    cache = {"k": big_k, "v": big_v}
    # attend against the whole cache; kv_offset makes query absolute
    # positions correct, and the causal mask kills both future tokens and
    # never-written (zero) slots beyond offset+t
    att = attn_ops.causal_attention(
        q, big_k[layer], big_v[layer], kv_offset=offset,
        window=cfg.attention_window,
        logit_softcap=cfg.attn_logit_softcap,
    ).reshape(b, t, nh * hd)
    att = L.dense(att, blk["wo"], blk.get("bo"))
    x = x + att

    h2 = gpt._norm(x, blk["ln2_scale"], blk.get("ln2_bias"), cfg)
    if cfg.n_experts:
        from mingpt_distributed_tpu.ops import moe

        m, _ = moe.moe_mlp(
            h2, blk["w_router"], blk["w_e1"], blk["w_e2"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            w_gate=blk.get("w_eg"),
        )
    elif cfg.swiglu:
        m = L.mlp_swiglu(h2, blk["w_gate"], blk["w_up"], blk["w_down"])
    else:
        m = L.mlp_gelu(h2, blk["w_fc"], blk.get("b_fc"), blk["w_proj"],
                       blk.get("b_proj"))
    return x + m, cache


def _forward_cached_hidden(
    params: gpt.Params, tokens: jax.Array, cache: Cache, offset, cfg: GPTConfig
) -> Tuple[jax.Array, Cache]:
    """Forward (B, T) tokens at absolute position ``offset`` through all
    layers, reading+writing the cache. Returns (final-norm hidden states
    (B, T, D), cache) — the LM head is applied separately (``_head_logits``)
    so callers that need logits at a *dynamic* position (the serving
    prefill reads position ``prompt_len - 1`` of a padded prompt) can slice
    the hidden states before paying the head matmul.

    The layer loop is a static python loop (n_layer is static, decode
    bodies are small) so each layer's cache update stays a one-slot
    in-place write — see _cached_block. Compile-time trade (ADVICE r5):
    unrolling puts every layer's body in the HLO, so prefill+decode program
    size and compile time grow roughly linearly with ``n_layer``. Fine at
    gpt2-124M (12 layers); a 48-layer gpt2-xl pays ~4x the compile of a
    scanned loop. If decode compile time ever binds for very deep configs,
    gate this on ``n_layer`` and fall back to a lax.scan over layers —
    accepting that the scan re-emits the whole cache per step (the r4/r5
    ~5.6 ms/token decode regression this unrolled loop exists to kill).
    """
    b, t = tokens.shape
    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["wte"][tokens]
    if not cfg.rope:
        pos = offset + jnp.arange(t)
        x = x + jnp.take(params["wpe"], pos, axis=0)
    x = x.astype(compute_dtype)

    for layer in range(cfg.n_layer):
        blk = jax.tree.map(lambda a, _l=layer: a[_l], params["blocks"])
        x, cache = _cached_block(x, blk, cache, layer, offset, cfg)
    x = gpt._norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg)
    return x, cache


def _head_logits(params: gpt.Params, x: jax.Array, cfg: GPTConfig) -> jax.Array:
    """LM head over (B, t, D) hidden states -> (B, t, V) fp32 logits
    (with the Gemma-2 final softcap when configured)."""
    w_head = params["wte"].T if cfg.tie_weights else params["head"]
    logits = jnp.einsum(
        "btd,dv->btv", x, w_head.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    return attn_ops.softcap(logits, cfg.final_logit_softcap)


def _forward_cached(
    params: gpt.Params, tokens: jax.Array, cache: Cache, offset, cfg: GPTConfig
) -> Tuple[jax.Array, Cache]:
    """Forward (B, T) tokens at position ``offset`` through all layers.
    Returns (last-position logits (B, V), cache). Thin composition of
    ``_forward_cached_hidden`` + ``_head_logits`` — the serving engine
    (serving/engine.py) shares the same two pieces."""
    x, cache = _forward_cached_hidden(params, tokens, cache, offset, cfg)
    logits = _head_logits(params, x[:, -1:], cfg)[:, 0]
    return logits, cache


def _select_next(
    logits: jax.Array, rng, temperature: float, do_sample: bool,
    top_k: Optional[int], top_p: Optional[float] = None,
) -> jax.Array:
    """Temperature / top-k / sample-vs-argmax — reference model.py:341-352 —
    plus nucleus (top-p) filtering as a beyond-parity extension."""
    logits = logits / jnp.maximum(temperature, 1e-8)
    if top_k is not None:
        k = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose preceding cumulative mass is < top_p; the top
        # token must survive unconditionally (top_p <= 0 would otherwise
        # mask every token and degenerate to token id 0), making top_p→0
        # equivalent to greedy; threshold at the smallest kept logit
        keep = (cum - probs) < top_p
        keep = keep.at[..., 0].set(True)
        kth = jnp.min(
            jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if do_sample:
        return jax.random.categorical(rng, logits, axis=-1)
    return jnp.argmax(logits, axis=-1)


@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "do_sample",
                     "top_k", "top_p"),
)
def _generate_jit(
    params, idx, rng, *, cfg: GPTConfig, max_new_tokens: int,
    temperature: float, do_sample: bool, top_k: Optional[int],
    top_p: Optional[float] = None,
):
    b, t0 = idx.shape
    cache = init_cache(cfg, b)
    step_keys = jax.random.split(rng, max_new_tokens)

    # prefill the prompt, pick the first new token
    logits, cache = _forward_cached(params, idx, cache, 0, cfg)
    first = _select_next(logits, step_keys[0], temperature, do_sample,
                         top_k, top_p)
    if max_new_tokens == 1:  # static
        return jnp.concatenate([idx, first[:, None]], axis=1)

    def step(carry, step_rng):
        tok, cache, pos = carry
        logits, cache = _forward_cached(params, tok[:, None], cache, pos, cfg)
        nxt = _select_next(logits, step_rng, temperature, do_sample,
                           top_k, top_p)
        return (nxt, cache, pos + 1), tok

    (last, _, _), toks = jax.lax.scan(
        step, (first, cache, jnp.asarray(t0)), step_keys[1:]
    )
    new_tokens = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1
    )
    return jnp.concatenate([idx, new_tokens], axis=1)


@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "do_sample",
                     "top_k", "top_p"),
)
def _generate_sliding_jit(
    params, idx, rng, *, cfg: GPTConfig, max_new_tokens: int,
    temperature: float, do_sample: bool, top_k: Optional[int],
    top_p: Optional[float] = None,
):
    """Reference-semantics sliding-window decode (model.py:336-337): every
    step forwards the last ``block_size`` tokens with positions 0..len-1.
    Static shapes: the window buffer is always (B, block_size), left-aligned;
    causal masking makes the garbage beyond ``length`` invisible to the
    read-out position. Returns only the (B, max_new_tokens) new tokens."""
    b, t0 = idx.shape  # t0 <= block_size (caller crops)
    bs = cfg.block_size
    window = jnp.zeros((b, bs), jnp.int32)
    window = jax.lax.dynamic_update_slice(window, idx, (0, 0))
    step_keys = jax.random.split(rng, max_new_tokens)

    def step(carry, step_rng):
        window, length = carry
        logits_all, _ = gpt.forward(params, window, cfg)
        logits = jax.lax.dynamic_slice_in_dim(
            logits_all, length - 1, 1, axis=1
        )[:, 0]
        nxt = _select_next(
            logits, step_rng, temperature, do_sample, top_k, top_p
        ).astype(jnp.int32)
        full = length >= bs
        base = jnp.where(full, jnp.roll(window, -1, axis=1), window)
        pos = jnp.where(full, bs - 1, length)
        window = jax.lax.dynamic_update_slice(base, nxt[:, None], (0, pos))
        return (window, jnp.minimum(length + 1, bs)), nxt

    (_, _), toks = jax.lax.scan(
        step, (window, jnp.asarray(t0, jnp.int32)), step_keys
    )
    return jnp.moveaxis(toks, 0, 1)


def generate(
    params: gpt.Params,
    cfg: GPTConfig,
    idx,
    max_new_tokens: int,
    temperature: float = 1.0,
    do_sample: bool = False,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``idx`` (B, T0).

    Keeps the reference's signature and semantics (model.py:323-328),
    including unbounded generation past the context window; one compiled
    program per (prompt_len, max_new_tokens) pair thereafter. ``top_p``
    (nucleus sampling) is a beyond-parity extension.
    """
    idx = jnp.asarray(idx, dtype=jnp.int32)
    if idx.ndim == 1:
        idx = idx[None]
    if max_new_tokens < 1:
        return idx
    if rng is None:
        rng = jax.random.key(0)
    if idx.shape[1] + max_new_tokens <= cfg.block_size:
        # fits the window: KV-cached fast path (positions never slide)
        return _generate_jit(
            params, idx, rng, cfg=cfg, max_new_tokens=max_new_tokens,
            temperature=float(temperature), do_sample=bool(do_sample),
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p),
        )
    # overflow: reference-exact sliding window over the last block_size
    # tokens; the full prompt still heads the returned sequence
    new = _generate_sliding_jit(
        params, idx[:, -cfg.block_size:], rng, cfg=cfg,
        max_new_tokens=max_new_tokens, temperature=float(temperature),
        do_sample=bool(do_sample),
        top_k=None if top_k is None else int(top_k),
        top_p=None if top_p is None else float(top_p),
    )
    return jnp.concatenate([idx, new], axis=1)
