"""from_pretrained: load OpenAI GPT-2 weights into the pytree.

The build's north star requires ``GPT.from_pretrained()`` with the upstream
minGPT surface (SURVEY §0 item 8 — the reference fork itself dropped it, so
this is reconstructed from the upstream API: ``from_pretrained('gpt2')`` ->
a model with OpenAI weights). TPU-natively that means: map a HuggingFace
``GPT2LMHeadModel`` state dict into our stacked-layer parameter pytree.

Layout facts the mapping encodes:
* HF GPT-2 uses Conv1D modules whose weight is stored (in_features,
  out_features) — already our ``dense`` convention, so **no transposes**
  (upstream minGPT, which uses nn.Linear's (out, in), must transpose; we
  must NOT — the classic from_pretrained bug inverted).
* ``c_attn`` fuses Q/K/V along the output axis: split into wq/wk/wv.
* per-layer tensors stack along a leading layer axis (our lax.scan layout).
* GPT-2 ties lm_head to wte -> cfg.tie_weights=True, no "head" param.
* activation is gelu_new (tanh approximation) — ops.layers.gelu matches.

``load_hf_state_dict`` is pure (dict -> pytree) and unit-tested against a
locally-constructed random-weight torch GPT2LMHeadModel for logit parity;
``from_pretrained`` wraps it with the transformers download/cache (requires
network or a pre-populated HF cache — gated accordingly).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

from mingpt_distributed_tpu.config import ConfigError, GPTConfig

Params = Dict[str, Any]

# upstream minGPT's supported set
PRETRAINED_MODELS = ("gpt2", "gpt2-medium", "gpt2-large", "gpt2-xl")


def config_for_pretrained(model_type: str, **overrides: Any) -> GPTConfig:
    if model_type not in PRETRAINED_MODELS:
        raise ConfigError(
            f"from_pretrained supports {PRETRAINED_MODELS}, got {model_type!r}"
        )
    base = dict(model_type=model_type, tie_weights=True)
    base.update(overrides)
    return GPTConfig.make(**base)


def _get(sd: Mapping[str, Any], key: str) -> np.ndarray:
    if key not in sd:
        raise KeyError(f"HF state dict missing {key!r}")
    v = sd[key]
    # torch tensor or ndarray
    return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)


def load_hf_state_dict(sd: Mapping[str, Any], cfg: GPTConfig) -> Params:
    """Map a GPT2LMHeadModel state dict onto our parameter pytree."""
    prefix = ""
    if any(k.startswith("transformer.") for k in sd):
        prefix = "transformer."
    d, nl, nh = cfg.n_embd, cfg.n_layer, cfg.n_head

    wte = _get(sd, f"{prefix}wte.weight")
    wpe = _get(sd, f"{prefix}wpe.weight")
    if wte.shape != (cfg.vocab_size, d) or wpe.shape[1] != d:
        raise ValueError(
            f"state dict shapes {wte.shape}/{wpe.shape} do not match config "
            f"({cfg.vocab_size}, {d})"
        )
    if wpe.shape[0] < cfg.block_size:
        raise ValueError(
            f"checkpoint supports {wpe.shape[0]} positions < block_size "
            f"{cfg.block_size}"
        )
    wpe = wpe[: cfg.block_size]

    def stack(fmt: str) -> np.ndarray:
        return np.stack([_get(sd, prefix + fmt.format(i)) for i in range(nl)])

    c_attn_w = stack("h.{}.attn.c_attn.weight")  # (L, D, 3D) — (in, out)
    c_attn_b = stack("h.{}.attn.c_attn.bias")    # (L, 3D)
    wq, wk, wv = np.split(c_attn_w, 3, axis=2)
    bq, bk, bv = np.split(c_attn_b, 3, axis=1)

    blocks = {
        "ln1_scale": stack("h.{}.ln_1.weight"),
        "ln1_bias": stack("h.{}.ln_1.bias"),
        "wq": wq, "wk": wk, "wv": wv,
        "bq": bq, "bk": bk, "bv": bv,
        "wo": stack("h.{}.attn.c_proj.weight"),
        "bo": stack("h.{}.attn.c_proj.bias"),
        "ln2_scale": stack("h.{}.ln_2.weight"),
        "ln2_bias": stack("h.{}.ln_2.bias"),
        "w_fc": stack("h.{}.mlp.c_fc.weight"),
        "b_fc": stack("h.{}.mlp.c_fc.bias"),
        "w_proj": stack("h.{}.mlp.c_proj.weight"),
        "b_proj": stack("h.{}.mlp.c_proj.bias"),
    }
    params: Params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {k: np.asarray(v, dtype=np.float32) for k, v in blocks.items()},
        "lnf_scale": _get(sd, f"{prefix}ln_f.weight"),
        "lnf_bias": _get(sd, f"{prefix}ln_f.bias"),
    }
    params["wte"] = np.asarray(params["wte"], dtype=np.float32)
    params["wpe"] = np.asarray(params["wpe"], dtype=np.float32)
    params["lnf_scale"] = np.asarray(params["lnf_scale"], dtype=np.float32)
    params["lnf_bias"] = np.asarray(params["lnf_bias"], dtype=np.float32)
    if not cfg.tie_weights:
        # untied variant: materialise the head from the (tied) lm_head/wte
        head = sd.get("lm_head.weight")
        head = _get(sd, "lm_head.weight") if head is not None else params["wte"]
        params["head"] = np.asarray(head, dtype=np.float32).T.copy()
    return params


def load_hf_llama_state_dict(sd: Mapping[str, Any], cfg: GPTConfig) -> Params:
    """Map a HF LlamaForCausalLM state dict onto our pytree.

    Llama uses nn.Linear, whose weight is stored (out_features, in_features)
    — the OPPOSITE of GPT-2's Conv1D — so every projection transposes here
    (and none do in load_hf_state_dict). RMSNorm scales and the embedding
    map straight across; rotary tables are computed, not stored.
    """
    if not (cfg.rope and cfg.swiglu and cfg.rmsnorm):
        raise ValueError("llama mapping expects rope+swiglu+rmsnorm config")
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""
    nl = cfg.n_layer

    wte = _get(sd, f"{prefix}embed_tokens.weight")
    if wte.shape != (cfg.vocab_size, cfg.n_embd):
        raise ValueError(
            f"embed_tokens {wte.shape} != ({cfg.vocab_size}, {cfg.n_embd})"
        )

    def stack_t(fmt: str) -> np.ndarray:
        # (out, in) -> (in, out), stacked over layers
        return np.stack(
            [_get(sd, prefix + fmt.format(i)).T for i in range(nl)]
        )

    def stack(fmt: str) -> np.ndarray:
        return np.stack([_get(sd, prefix + fmt.format(i)) for i in range(nl)])

    blocks = {
        "ln1_scale": stack("layers.{}.input_layernorm.weight"),
        "ln2_scale": stack("layers.{}.post_attention_layernorm.weight"),
        "wq": stack_t("layers.{}.self_attn.q_proj.weight"),
        "wk": stack_t("layers.{}.self_attn.k_proj.weight"),
        "wv": stack_t("layers.{}.self_attn.v_proj.weight"),
        "wo": stack_t("layers.{}.self_attn.o_proj.weight"),
        "w_gate": stack_t("layers.{}.mlp.gate_proj.weight"),
        "w_up": stack_t("layers.{}.mlp.up_proj.weight"),
        "w_down": stack_t("layers.{}.mlp.down_proj.weight"),
    }
    params: Params = {
        "wte": np.asarray(wte, dtype=np.float32),
        "blocks": {k: np.asarray(v, dtype=np.float32) for k, v in blocks.items()},
        "lnf_scale": np.asarray(_get(sd, f"{prefix}norm.weight"), np.float32),
    }
    if not cfg.tie_weights:
        params["head"] = np.asarray(
            _get(sd, "lm_head.weight"), np.float32
        ).T.copy()
    return params


def from_pretrained(
    model_type: str = "gpt2", **config_overrides: Any
) -> Tuple[GPTConfig, Params]:
    """Load OpenAI GPT-2 weights via the transformers hub/cache.

    Returns (cfg, params) — the pytree is ready for gpt.forward /
    generate.generate, and serves as the logit-parity oracle for tests.
    Requires network access or a pre-populated HF cache; raises RuntimeError
    with guidance otherwise.
    """
    cfg = config_for_pretrained(model_type, **config_overrides)
    try:
        from transformers import GPT2LMHeadModel
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(f"transformers unavailable: {e}") from None
    try:
        hf = GPT2LMHeadModel.from_pretrained(model_type)
    except Exception as e:
        raise RuntimeError(
            f"could not load {model_type!r} weights (offline? set HF_HOME to "
            f"a populated cache): {e}"
        ) from None
    return cfg, load_hf_state_dict(hf.state_dict(), cfg)
