from mingpt_distributed_tpu.models.api import GPT  # noqa: F401
