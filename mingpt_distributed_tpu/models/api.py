"""GPT — the object-style facade over the functional core.

The reference's public surface is a torch module: ``GPT(config)`` with
``forward(inputs, targets=None) -> (logits, loss)`` and
``generate(idx, max_new_tokens, temperature, do_sample, top_k)``
(/root/reference/mingpt/model.py:234-356), plus upstream minGPT's
``GPT.from_pretrained('gpt2*')`` (north-star requirement, SURVEY §0 item 8).

This class keeps those signatures exactly while the state lives where the
TPU wants it — a params pytree the trainer/sharding machinery can own. The
facade is deliberately thin: anything performance-critical goes through the
same jitted pure functions (models/gpt.py, models/generate.py) the trainer
uses; the class only carries (cfg, params, rng).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import generate as _generate
from mingpt_distributed_tpu.models import gpt as _gpt
from mingpt_distributed_tpu.telemetry.spans import log_event


class GPT:
    """Decoder-only transformer with the reference's public surface."""

    def __init__(
        self,
        config: GPTConfig,
        params: Optional[Any] = None,
        *,
        seed: int = 0,
    ):
        self.config = config.resolved()
        self.params = (
            params
            if params is not None
            else _gpt.init(jax.random.key(seed), self.config)
        )
        # construction-time report, as the reference prints param count +
        # model MB (model.py:257-259) — routed through log_event so the
        # line is process-prefixed and lands in the span ring (GL010)
        log_event(_gpt.model_size_report(self.params, self.config))

    # -- torch-module-flavoured API ------------------------------------
    def forward(
        self,
        inputs,
        targets=None,
        *,
        rng: Optional[jax.Array] = None,
        deterministic: bool = True,
    ) -> Tuple[jax.Array, Optional[jax.Array]]:
        return _gpt.forward(
            self.params, inputs, self.config, targets=targets, rng=rng,
            deterministic=deterministic,
        )

    __call__ = forward

    def generate(
        self,
        idx,
        max_new_tokens: int,
        temperature: float = 1.0,
        do_sample: bool = False,
        top_k: Optional[int] = None,
        *,
        top_p: Optional[float] = None,
        rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Reference signature (model.py:323-328), KV-cached compiled decode;
        keyword-only ``top_p`` (nucleus sampling) is a beyond-parity extra."""
        return _generate.generate(
            self.params, self.config, idx, max_new_tokens,
            temperature=temperature, do_sample=do_sample, top_k=top_k,
            top_p=top_p, rng=rng,
        )

    @classmethod
    def from_pretrained(cls, model_type: str = "gpt2", **overrides) -> "GPT":
        """Upstream-minGPT API: load OpenAI GPT-2 weights."""
        from mingpt_distributed_tpu.models.pretrained import from_pretrained

        cfg, params = from_pretrained(model_type, **overrides)
        return cls(cfg, params)

    @property
    def num_params(self) -> int:
        return _gpt.param_count(self.params)
