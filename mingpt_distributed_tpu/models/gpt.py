"""GPT as pure functions over a parameter pytree.

TPU-first re-design of the reference model (/root/reference/mingpt/model.py:
GPTEmbedding :193-231, Block :171-189, MultiHeadSelfAttention :125-168,
GPT :234-356). The architecture matches the reference's *intent* — pre-LN
decoder-only transformer, learned token + (zero-init) learned positional
embeddings, 4x GELU MLP, final LayerNorm, bias-free LM head, N(0, 0.02) init
with GPT-2 residual-path scaling 0.02/sqrt(2L) — with the reference's latent
model bugs (B3-B6, B16: broken asserts, pos-embedding indexed by token value,
MLP activation after both linears, non-masking float causal mask) fixed by
construction, and the mechanism re-thought for XLA:

* the model is data — a pytree of float32 arrays — and ``forward`` is a pure
  function, so sharding enters from *outside* via NamedSharding on the pytree
  (preserving the reference's parallelism-unaware-model layering, SURVEY §1-L2);
* per-layer parameters are stacked along a leading layer axis and the block
  is applied with ``lax.scan`` — one block compiled once, not n_layer copies
  unrolled, and ``jax.checkpoint`` (cfg.remat) slots in per scan step;
* activations run in cfg.dtype (bfloat16 on the MXU); normalisations, softmax
  and the loss run in float32;
* no (T, T) mask buffer per layer: causality is computed inside attention.

Llama-retrofit toggles (rope/swiglu/rmsnorm/GQA — BASELINE config #5) reuse
the same skeleton.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.ops import layers as L
from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init(key: jax.Array, cfg: GPTConfig) -> Params:
    """Materialise the parameter pytree.

    Init scheme is the reference's (model.py:298-307, 252-256): weights
    N(0, 0.02), biases 0, LayerNorm identity, positional embedding zeros
    (model.py:209-214), residual-path projections N(0, 0.02/sqrt(2L)).
    Runs fine under jit with out_shardings so huge models can be born sharded.
    """
    cfg.validate()
    d, nl, nh = cfg.n_embd, cfg.n_layer, cfg.n_head
    hd, kv = cfg.head_dim, cfg.kv_heads
    ffn = int(cfg.ffn_mult * d)
    use_bias = not (cfg.swiglu or cfg.rmsnorm)  # GPT-2 mode has biases everywhere

    keys = iter(jax.random.split(key, 32))
    std = 0.02
    resid_std = 0.02 / math.sqrt(2 * nl)

    def normal(k, shape, s=std):
        return jax.random.normal(k, shape, dtype=jnp.float32) * s

    blocks: Params = {
        "ln1_scale": jnp.ones((nl, d)),
        "ln2_scale": jnp.ones((nl, d)),
        "wq": normal(next(keys), (nl, d, nh * hd)),
        "wk": normal(next(keys), (nl, d, kv * hd)),
        "wv": normal(next(keys), (nl, d, kv * hd)),
        "wo": normal(next(keys), (nl, nh * hd, d), resid_std),
    }
    if not cfg.rmsnorm:
        blocks["ln1_bias"] = jnp.zeros((nl, d))
        blocks["ln2_bias"] = jnp.zeros((nl, d))
    if use_bias:
        blocks.update(
            bq=jnp.zeros((nl, nh * hd)),
            bk=jnp.zeros((nl, kv * hd)),
            bv=jnp.zeros((nl, kv * hd)),
            bo=jnp.zeros((nl, d)),
        )
    if cfg.n_experts:
        e = cfg.n_experts
        blocks.update(
            w_router=normal(next(keys), (nl, d, e)),
            w_e1=normal(next(keys), (nl, e, d, ffn)),
            w_e2=normal(next(keys), (nl, e, ffn, d), resid_std),
        )
        if cfg.swiglu:  # Mixtral-style SwiGLU experts
            blocks["w_eg"] = normal(next(keys), (nl, e, d, ffn))
    elif cfg.swiglu:
        blocks.update(
            w_gate=normal(next(keys), (nl, d, ffn)),
            w_up=normal(next(keys), (nl, d, ffn)),
            w_down=normal(next(keys), (nl, ffn, d), resid_std),
        )
    else:
        blocks.update(
            w_fc=normal(next(keys), (nl, d, ffn)),
            w_proj=normal(next(keys), (nl, ffn, d), resid_std),
        )
        if use_bias:
            blocks.update(b_fc=jnp.zeros((nl, ffn)), b_proj=jnp.zeros((nl, d)))

    params: Params = {
        "wte": normal(next(keys), (cfg.vocab_size, d)),
        "blocks": blocks,
        "lnf_scale": jnp.ones((d,)),
    }
    if not cfg.rope:
        params["wpe"] = jnp.zeros((cfg.block_size, d))
    if not cfg.rmsnorm:
        params["lnf_bias"] = jnp.zeros((d,))
    if not cfg.tie_weights:
        params["head"] = normal(next(keys), (d, cfg.vocab_size))
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _attention_dispatch(cfg: GPTConfig, mesh=None):
    """Select the attention implementation named by cfg.attention.

    "einsum" is the oracle (ops/attention.py). "flash" is the Pallas
    blockwise kernel (ops/flash_attention.py). "ring" is the
    sequence-parallel path (parallel/ring_attention.py) — it needs the mesh,
    which is the one piece of parallelism context that can't stay outside
    the model: the ring's collectives live inside attention itself.
    """
    if cfg.attention == "einsum":
        return attn_ops.causal_attention
    if cfg.attention == "flash":
        from mingpt_distributed_tpu.ops import flash_attention

        if mesh is None:
            return flash_attention.causal_attention

        # The Pallas kernel is a single program whose packed-lane cells
        # (128 lanes = up to 128/hd sub-heads, ops/flash_attention._btd_pack)
        # must never be SPLIT by the partitioner: GSPMD sharding q's head
        # axis over tp can land a shard boundary inside one cell, and the
        # interpret-mode lowering of the kernel then computes garbage
        # (observed: head_dim=16 → pack=8 one-cell geometry, fwd AND grads
        # wrong under tp=2 — the llama hd16/GQA divergence; head_dim=64 →
        # pack=2 only survived because tp=2 happened to split on a cell
        # boundary). Batch-dim sharding is the one partitioning the kernel
        # is safe under, so pin q/k/v/out to batch-only: a no-op for the
        # dp/fsdp training path, an explicit head all-gather for the
        # non-tp-manual tp>1 corner (correct first; the aligned-head tp
        # cases run the manual-tp path and never see this wrapper).
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PSpec

        from mingpt_distributed_tpu.parallel.mesh import BATCH_AXES

        batch_only = NamedSharding(mesh, PSpec(BATCH_AXES))

        def flash_batch_partitioned(q, k, v, **kw):
            cst = lambda a: jax.lax.with_sharding_constraint(a, batch_only)
            out = flash_attention.causal_attention(
                cst(q), cst(k), cst(v), **kw)
            return jax.lax.with_sharding_constraint(out, batch_only)

        return flash_batch_partitioned
    if cfg.attention == "ring":
        from mingpt_distributed_tpu.parallel import ring_attention

        return lambda q, k, v, **kw: ring_attention.ring_causal_attention(
            q, k, v, mesh, **kw
        )
    if cfg.attention == "ulysses":
        from mingpt_distributed_tpu.parallel import ulysses

        return lambda q, k, v, **kw: ulysses.ulysses_causal_attention(
            q, k, v, mesh, **kw
        )
    raise NotImplementedError(f"attention={cfg.attention!r}")


def _manual_sp_attention(cfg: GPTConfig):
    """Per-shard sequence-parallel attention for use *inside* an enclosing
    shard_map region (the pipeline): the ring / Ulysses shard bodies run
    directly over the manual ``sp`` axis — their public wrappers would try
    to open a nested shard_map, which JAX forbids."""
    from mingpt_distributed_tpu.parallel import ring_attention, ulysses

    def fn(q, k, v, *, attn_pdrop=0.0, dropout_key=None, deterministic=True,
           window=None, logit_softcap=None):
        # attention dropout composes here too (VERDICT r3 weak #4): the
        # shard bodies take (pdrop, key) directly and fold the chunk /
        # head-group index in, so every (pair, head) mask is drawn exactly
        # once. NOTE: under pp the enclosing body_pp has already folded the
        # sp/batch shard indices into the key, so unlike the public
        # wrappers the mask is NOT a pure function of the global pair id —
        # statistically identical dropout, but a dense oracle cannot
        # reproduce the masks blockwise here (it can for the public path,
        # see tests/test_ring_attention.py::..._matches_blockwise_oracle)
        drop = (not deterministic) and attn_pdrop > 0.0 \
            and dropout_key is not None
        h, hd = q.shape[2], q.shape[3]
        k2 = attn_ops.repeat_kv(k, h // k.shape[2])
        v2 = attn_ops.repeat_kv(v, h // v.shape[2])
        if cfg.attention == "ring":
            return ring_attention._ring_shard(
                q, k2, v2, axis_name="sp", scale=1.0 / math.sqrt(hd),
                window=window, softcap=logit_softcap,
                pdrop=attn_pdrop if drop else 0.0,
                key=dropout_key if drop else None,
            )
        return ulysses._ulysses_shard(q, k2, v2, axis_name="sp",
                                      window=window, softcap=logit_softcap,
                                      pdrop=attn_pdrop if drop else 0.0,
                                      key=dropout_key if drop else None)

    return fn


def _norm(x, scale, bias, cfg: GPTConfig):
    if cfg.rmsnorm:
        return L.rms_norm(x, scale, eps=cfg.norm_eps)
    return L.layer_norm(x, scale, bias, eps=cfg.norm_eps)


def _block(
    x: jax.Array,
    blk: Params,
    cfg: GPTConfig,
    rope: Optional[Tuple[jax.Array, jax.Array]],
    drop_key: Optional[jax.Array],
    deterministic: bool,
    mesh=None,
    attn_fn=None,  # override (e.g. manual sp attention inside the pipeline)
    tp_axis: Optional[str] = None,  # manual megatron-tp inside shard_map
    ep_axis: Optional[str] = None,  # manual expert parallelism in shard_map
) -> Tuple[jax.Array, jax.Array]:
    """One pre-LN transformer block: x + attn(ln1(x)); x + mlp(ln2(x)).

    Returns (x, aux): aux is the MoE load-balancing loss for this layer
    (zero for dense MLPs) — accumulated across layers by the caller.

    ``tp_axis`` (inside an enclosing shard_map, e.g. the pipeline) runs the
    megatron recipe manually: this shard's weights hold n_head/tp heads and
    ffn/tp columns (column-parallel in, row-parallel out), activations stay
    replicated over tp, and the only tp collectives are one psum per
    residual branch (after wo and after the MLP down-projection), applied
    *before* the output bias so the bias isn't multiplied by tp."""
    b, t, d = x.shape
    nh, kv, hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
    if tp_axis is not None:
        assert not cfg.n_experts, "tp_axis doesn't compose with MoE blocks"
        tp_n = jax.lax.psum(1, tp_axis)
        nh, kv = nh // tp_n, kv // tp_n
    if drop_key is not None:
        k_attn, k_resid1, k_resid2 = jax.random.split(drop_key, 3)
        if tp_axis is not None:
            # attention dropout acts on this shard's local heads — fold the
            # shard index in so head h of shard j draws a different mask
            # than head h of shard 0 (residual dropout keys must stay
            # replicated: those activations are identical across tp)
            k_attn = jax.random.fold_in(k_attn, jax.lax.axis_index(tp_axis))
    else:
        k_attn = k_resid1 = k_resid2 = None

    h = _norm(x, blk["ln1_scale"], blk.get("ln1_bias"), cfg)
    q = L.dense(h, blk["wq"], blk.get("bq")).reshape(b, t, nh, hd)
    k = L.dense(h, blk["wk"], blk.get("bk")).reshape(b, t, kv, hd)
    v = L.dense(h, blk["wv"], blk.get("bv")).reshape(b, t, kv, hd)
    if rope is not None:
        cos, sin = rope
        q = attn_ops.apply_rope(q, cos, sin)
        k = attn_ops.apply_rope(k, cos, sin)
    # window/softcap compose with every attention impl, including the
    # manual-sp attn_fn override inside pipeline stages
    attn_kw = {}
    if cfg.attention_window:
        attn_kw["window"] = cfg.attention_window
    if cfg.attn_logit_softcap:
        attn_kw["logit_softcap"] = cfg.attn_logit_softcap
    att = (attn_fn or _attention_dispatch(cfg, mesh))(
        q, k, v,
        attn_pdrop=cfg.attn_pdrop,
        dropout_key=k_attn,
        deterministic=deterministic,
        **attn_kw,
    ).reshape(b, t, nh * hd)
    if tp_axis is not None:
        att = jax.lax.psum(L.dense(att, blk["wo"]), tp_axis)
        if blk.get("bo") is not None:
            att = att + blk["bo"].astype(att.dtype)
    else:
        att = L.dense(att, blk["wo"], blk.get("bo"))
    att = L.dropout(att, cfg.resid_pdrop, k_resid1, deterministic)
    x = x + att

    h2 = _norm(x, blk["ln2_scale"], blk.get("ln2_bias"), cfg)
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        from mingpt_distributed_tpu.ops import moe

        m, aux = moe.moe_mlp(
            h2, blk["w_router"], blk["w_e1"], blk["w_e2"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            w_gate=blk.get("w_eg"), ep_axis=ep_axis,
        )
    elif cfg.swiglu:
        if tp_axis is not None:
            inner = jax.nn.silu(L.dense(h2, blk["w_gate"])) * L.dense(h2, blk["w_up"])
            m = jax.lax.psum(L.dense(inner, blk["w_down"]), tp_axis)
        else:
            m = L.mlp_swiglu(h2, blk["w_gate"], blk["w_up"], blk["w_down"])
    else:
        if tp_axis is not None:
            inner = L.gelu(L.dense(h2, blk["w_fc"], blk.get("b_fc")))
            m = jax.lax.psum(L.dense(inner, blk["w_proj"]), tp_axis)
            if blk.get("b_proj") is not None:
                m = m + blk["b_proj"].astype(m.dtype)
        else:
            m = L.mlp_gelu(h2, blk["w_fc"], blk.get("b_fc"), blk["w_proj"], blk.get("b_proj"))
    m = L.dropout(m, cfg.resid_pdrop, k_resid2, deterministic)
    return x + m, aux


def forward(
    params: Params,
    tokens: jax.Array,  # (B, T) int32
    cfg: GPTConfig,
    *,
    targets: Optional[jax.Array] = None,  # (B, T) int32, -1 = ignore
    rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    mesh=None,  # required only for attention="ring" (see _attention_dispatch)
    return_logits: bool = True,
) -> Tuple[Optional[jax.Array], Optional[jax.Array]]:
    """Full forward pass -> (logits (B, T, V) float32, loss or None).

    Same contract as the reference's GPT.forward (model.py:309-320): returns
    logits always, plus mean cross-entropy over targets != -1 when targets
    are given. ``return_logits=False`` (the trainer's loss-only mode)
    returns ``(None, loss)`` and — when ``cfg.loss_chunks`` applies — never
    materialises the (B, T, V) logits at all: the LM head + softmax run per
    sequence chunk under jax.checkpoint (see chunked_cross_entropy).
    """
    b, t = tokens.shape
    if t > cfg.block_size:  # static shape — checked at trace time (B3 intent)
        raise ValueError(f"sequence length {t} > block_size {cfg.block_size}")
    if not deterministic and rng is None:
        raise ValueError("training-mode forward needs rng for dropout")

    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["wte"][tokens]  # (B, T, D) fp32 gather
    if not cfg.rope:
        # slice by *position*, add (the B4 fix: reference indexed pos table
        # by token values and called a Parameter)
        x = x + params["wpe"][:t]
    if deterministic:
        emb_key = None
    else:
        rng, emb_key = jax.random.split(rng)
    x = L.dropout(x, cfg.embd_pdrop, emb_key, deterministic)
    x = x.astype(compute_dtype)

    rope = None
    if cfg.rope:
        rope = attn_ops.rope_tables(jnp.arange(t), cfg.head_dim, cfg.rope_theta)

    nl = cfg.n_layer
    if deterministic:
        def body(carry, blk):
            xc, aux = carry
            y, a = _block(xc, blk, cfg, rope, None, True, mesh)
            return (y, aux + a), None
        xs = params["blocks"]
    else:
        layer_keys = jax.random.split(rng, nl)
        def body(carry, scanned):
            blk, key = scanned
            xc, aux = carry
            y, a = _block(xc, blk, cfg, rope, key, False, mesh)
            return (y, aux + a), None
        xs = (params["blocks"], layer_keys)

    step = jax.checkpoint(body) if cfg.remat else body

    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        # pipeline stages over the pp axis (parallel/pipeline.py): the same
        # scanned block, applied to each stage's layer shard per microbatch.
        # rope tables travel as explicit replicated consts — shard_map must
        # see every traced value it uses.
        from mingpt_distributed_tpu.parallel import pipeline

        sp = mesh.shape.get("sp", 1)
        seq_sharded = cfg.attention in ("ring", "ulysses") and sp > 1
        if seq_sharded:
            # inside the manual region there is no oracle fallback, so the
            # shard bodies' applicability conditions become hard errors
            # (attention dropout is supported: _manual_sp_attention routes
            # it to the shard bodies' einsum/dense-local dropped paths)
            if t % sp:
                raise ValueError(f"T={t} not divisible by sp={sp} under pp")
            # (ulysses head-divisibility is checked below, tp-aware)
        # ep x pp (VERDICT r3 next #6): expert leaves (w_e*) keep their ep
        # sharding through xs_specs; the MoE runs manual expert parallelism
        # inside the region (two all_to_alls over ep — ops/moe.py ep_axis)
        ep_n = mesh.shape.get("ep", 1)
        ep_manual = bool(cfg.n_experts) and ep_n > 1
        if ep_manual and cfg.n_experts % ep_n:
            raise ValueError(
                f"n_experts={cfg.n_experts} not divisible by ep={ep_n}"
            )
        manual_attn = _manual_sp_attention(cfg) if seq_sharded else None

        # --- keep tp/fsdp sharding LIVE inside the pipeline region --------
        # (VERDICT r2 next #5). Megatron-tp is run manually when every
        # split dimension divides; otherwise tp falls back to gathered
        # (replicated) stage params, exactly the previous behaviour.
        # fsdp stays sharded per-leaf regardless and is all-gathered
        # per *layer* inside the scan (ZeRO-3-style JIT gather: one layer's
        # params live at a time; remat re-gathers in backward).
        tp_n = mesh.shape.get("tp", 1)
        ffn_dim = int(cfg.ffn_mult * cfg.n_embd)
        tp_manual = (
            tp_n > 1
            and not cfg.n_experts
            and cfg.n_head % tp_n == 0
            and cfg.kv_heads % tp_n == 0
            and ffn_dim % tp_n == 0
        )
        if cfg.attention == "ulysses" and seq_sharded:
            local_heads = cfg.n_head // tp_n if tp_manual else cfg.n_head
            if local_heads % sp:
                raise ValueError(
                    f"ulysses needs (n_head/tp) % sp == 0 "
                    f"(got {local_heads} % {sp})"
                )
        from mingpt_distributed_tpu.parallel import mesh as mesh_lib

        def leaf_spec(path, leaf):
            from jax.sharding import PartitionSpec as PSpec

            rule = mesh_lib.PARAM_RULES[mesh_lib.leaf_name(path)]
            if not tp_manual:  # drop tp: apply_stack runs dense math
                rule = PSpec(*(
                    None if ax == "tp" else ax for ax in rule
                ))
            return mesh_lib.shard_by_rule(mesh, leaf.shape, rule).spec

        blocks_specs = jax.tree_util.tree_map_with_path(
            leaf_spec, params["blocks"]
        )
        name_to_spec = {}
        jax.tree_util.tree_map_with_path(
            lambda path, s: name_to_spec.setdefault(
                mesh_lib.leaf_name(path), s
            ),
            blocks_specs,
        )
        xs_specs = (
            blocks_specs if deterministic
            else (blocks_specs, jax.sharding.PartitionSpec("pp"))
        )

        def gather_fsdp(blk):
            """All-gather ONE layer's params over fsdp at point of use
            (leading layer axis already consumed by the scan)."""

            def g(path, leaf):
                spec = name_to_spec[mesh_lib.leaf_name(path)]
                for dim, ax in enumerate(spec[1:]):  # [0] = layer axis
                    if ax == "fsdp":
                        return jax.lax.all_gather(
                            leaf, "fsdp", axis=dim, tiled=True
                        )
                return leaf

            return jax.tree_util.tree_map_with_path(g, blk)

        def apply_stack(x_mb, xs_local, consts, mb_idx):
            if cfg.rope:
                cos, sin = consts
                if seq_sharded:
                    # this shard's rows of the (global-T) rope tables
                    c = x_mb.shape[1]
                    i0 = jax.lax.axis_index("sp") * c
                    cos = jax.lax.dynamic_slice_in_dim(cos, i0, c)
                    sin = jax.lax.dynamic_slice_in_dim(sin, i0, c)
                rope_c = (cos, sin)
            else:
                rope_c = None

            def run(carry, blk, key):
                xc, aux = carry
                blk = gather_fsdp(blk)
                y, a = _block(xc, blk, cfg, rope_c, key, deterministic,
                              attn_fn=manual_attn,
                              tp_axis="tp" if tp_manual else None,
                              ep_axis="ep" if ep_manual else None)
                return (y, aux + a)

            if deterministic:
                def body_pp(carry, blk):
                    return run(carry, blk, None), None
            else:
                def body_pp(carry, scanned):
                    blk, key = scanned
                    # decorrelate dropout across microbatches: the same
                    # layer key is applied to every microbatch otherwise
                    key = jax.random.fold_in(key, mb_idx)
                    # ...and across batch shards: the pipeline's shard_map
                    # manualises every mesh axis, so dp/fsdp/ep shards hold
                    # DIFFERENT rows of the same microbatch but would draw
                    # identical masks from the replicated layer key (the
                    # dense GSPMD path draws per-global-row)
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(BATCH_AXES)
                    )
                    if seq_sharded:
                        # ...and across sequence shards: each sp shard
                        # holds different positions of the same tensor
                        key = jax.random.fold_in(
                            key, jax.lax.axis_index("sp")
                        )
                    return run(carry, blk, key), None
            step_pp = jax.checkpoint(body_pp) if cfg.remat else body_pp
            (y, aux), _ = jax.lax.scan(
                step_pp, (x_mb, jnp.zeros((), jnp.float32)), xs_local,
                unroll=cfg.scan_unroll,
            )
            return y, aux

        # pipeline aux = sum over layers, averaged over microbatches and
        # batch shards — the same quantity the single-device scan carries
        x, moe_aux = pipeline.pipeline_blocks(
            x, xs, rope if cfg.rope else (), apply_stack, mesh,
            n_microbatches=cfg.pp_microbatches,
            seq_sharded=seq_sharded,
            xs_specs=xs_specs,
            schedule=cfg.pp_schedule,
        )
    elif cfg.unroll_layers:
        # statically unrolled layer loop: same body (incl. remat wrapping),
        # but per-layer params/keys are static slices — no scan carry, no
        # dynamic-update-slice stacking of saved activations (see
        # config.unroll_layers)
        carry = (x, jnp.zeros((), jnp.float32))
        for i in range(nl):
            xi = jax.tree.map(lambda a: a[i], xs)
            carry, _ = step(carry, xi)
        x, moe_aux = carry
    else:
        (x, moe_aux), _ = jax.lax.scan(
            step, (x, jnp.zeros((), jnp.float32)), xs,
            unroll=cfg.scan_unroll,
        )

    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), cfg)
    w_head = params["wte"].T if cfg.tie_weights else params["head"]
    # snap the chunk count to the largest divisor of T <= loss_chunks, so an
    # awkward block_size degrades to fewer/larger chunks, not silently to
    # the dense (B, T, V) materialisation the feature exists to avoid
    nc = max(
        (d for d in range(1, cfg.loss_chunks + 1) if t % d == 0),
        default=1,
    )
    chunked = targets is not None and not return_logits and nc > 1

    logits = None
    if not chunked:
        logits = jnp.einsum(
            "btd,dv->btv", x, w_head.astype(x.dtype),
            preferred_element_type=jnp.float32,
        )
        logits = attn_ops.softcap(logits, cfg.final_logit_softcap)

    loss = None
    if targets is not None:
        if chunked:
            # loss-only mode: the LM head + softmax run per sequence chunk
            # under jax.checkpoint, so the full (B, T, V) fp32 logits
            # (1.6 GB at B=8/T=1024/V=50257 — the tensor that caps the
            # per-chip batch) never materialises, forward or backward.
            # When logits are requested they exist anyway, so dense CE
            # costs no extra memory — no chunking in that case.
            loss = chunked_cross_entropy(
                x, w_head.astype(x.dtype), targets, nc,
                softcap=cfg.final_logit_softcap,
                unroll=cfg.unroll_layers,
            )
        else:
            loss = cross_entropy(logits, targets)
        if cfg.n_experts:
            # per-layer-mean load-balancing loss (Switch Transformer)
            loss = loss + cfg.moe_aux_weight * moe_aux / nl
    if not return_logits:
        logits = None
    return logits, loss


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean CE over positions with target != -1 (reference model.py:316-319:
    F.cross_entropy(..., ignore_index=-1))."""
    valid = targets != -1
    safe = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return -(ll * valid).sum() / jnp.maximum(valid.sum(), 1)


def chunked_cross_entropy(
    x: jax.Array, w_head: jax.Array, targets: jax.Array, n_chunks: int,
    softcap: Optional[float] = None,
    unroll: bool = False,
) -> jax.Array:
    """Same math as ``cross_entropy(x @ w_head, targets)``, but the head
    matmul + softmax run per sequence chunk under ``jax.checkpoint``:
    peak logits memory is (B, T/n_chunks, V) and the backward recomputes
    each chunk's logits instead of storing them. Trades one extra head
    matmul (in backward) for ~2x(B,T,V) fp32 of HBM — the dominant
    activation for GPT-2-sized vocabularies.

    The per-chunk loss is ``sum(lse - logit_target)`` — two reductions
    over the chunk logits — rather than ``log_softmax`` + gather, which
    would materialise a full (B, c, V) log-prob tensor only to read one
    column of it (round-4 trace: the CE machinery cost ~2.6x its matmul
    ideal).

    ``unroll=True`` replaces the chunk lax.scan with a statically unrolled
    python loop over direct slices of ``x`` — no (n, B, c, D) transposed
    copy of the activations, no while-loop overhead, and XLA can overlap
    chunk k's matmul with chunk k-1's reductions (same rationale as
    ``config.unroll_layers``, which the trainer threads through here).
    """
    b, t, d = x.shape
    if t % n_chunks:
        # the unrolled slices would silently drop the tail (the scan path's
        # reshape would fail anyway) — forward() snaps nc to a divisor of T
        raise ValueError(f"T={t} not divisible by n_chunks={n_chunks}")
    c = t // n_chunks

    def chunk_loss(xc, tc):
        logits = jnp.einsum(
            "bcd,dv->bcv", xc, w_head, preferred_element_type=jnp.float32
        )
        logits = attn_ops.softcap(logits, softcap)
        valid = tc != -1
        safe = jnp.where(valid, tc, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)  # (B, c) fp32
        s_t = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return ((lse - s_t) * valid).sum(), valid.sum()

    ck = jax.checkpoint(chunk_loss)

    if unroll:
        tot = jnp.zeros((), jnp.float32)
        cnt = jnp.zeros((), jnp.int32)
        for i in range(n_chunks):
            li, ci = ck(x[:, i * c:(i + 1) * c], targets[:, i * c:(i + 1) * c])
            tot, cnt = tot + li, cnt + ci
        return tot / jnp.maximum(cnt, 1)

    xs = x.reshape(b, n_chunks, c, d).swapaxes(0, 1)  # (n, B, c, D)
    ts = targets.reshape(b, n_chunks, c).swapaxes(0, 1)

    def body(carry, xt):
        li, ci = ck(*xt)
        return (carry[0] + li, carry[1] + ci), None

    (tot, cnt), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (xs, ts),
    )
    return tot / jnp.maximum(cnt, 1)


# ---------------------------------------------------------------------------
# Reporting (reference C10: print_model_size, model.py:21-33, 257-259)
# ---------------------------------------------------------------------------


def param_count(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


def model_size_report(params: Params, cfg: GPTConfig) -> str:
    n = param_count(params)
    mb = sum(int(p.size) * p.dtype.itemsize for p in jax.tree.leaves(params)) / 2**20
    return (
        f"GPT: {cfg.n_layer}L/{cfg.n_head}H/{cfg.n_embd}d, "
        f"block {cfg.block_size}, vocab {cfg.vocab_size} — "
        f"{n/1e6:.2f}M params, {mb:.1f} MB (fp32 master)"
    )
