"""Shims for jax API drift between the version this codebase targets and
the version actually installed in an environment.

The repo tracks current jax surface names (``jax.shard_map``,
``pallas.tpu.CompilerParams``); older jaxlibs ship the same
functionality under the pre-promotion names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``,
``pallas.tpu.TPUCompilerParams``). Routing every use through this module
means an environment running either vintage imports and passes tier-1
instead of dying on AttributeError at import/trace time — dependency
drift is an availability bug like any other.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as _pltpu

#: pallas TPU compiler-params constructor under either name
TPUCompilerParams = getattr(
    _pltpu, "CompilerParams", None
) or _pltpu.TPUCompilerParams


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` when present, else the experimental spelling.

    ``check_vma`` (the promoted API's name) maps onto the experimental
    API's ``check_rep``; None lets each implementation use its default.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
