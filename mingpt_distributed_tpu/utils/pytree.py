"""Small pytree helpers shared across the package."""

from __future__ import annotations

import jax


def leaf_name(path) -> str:
    """Final key of a tree_map_with_path path — the parameter's name.

    Works for dict keys (DictKey), dataclass/namedtuple fields (GetAttrKey)
    and sequence indices (SequenceKey).
    """
    last = path[-1]
    return getattr(last, "key", getattr(last, "name", str(last)))
