"""The training loop — SPMD re-design of the reference's GPTTrainer
(/root/reference/mingpt/trainer.py:40-183).

What the reference does per batch — H2D copy, forward, backward (DDP
all-reduce), clip, step, then a blocking ``loss.item()`` D2H sync
(trainer.py:118-133, SURVEY §3.1's hot loop) — compiles here into ONE XLA
program: ``train_step`` = forward + backward + psum(grads over the batch axes)
+ clip + AdamW update, jitted with donated state, so the chip never waits on
the host inside the loop and metrics are fetched only every ``log_every``
steps (the per-batch sync is SURVEY §3.1's flagged throughput bug — not
reproduced).

Parallelism is carried by NamedShardings on the state/batch pytrees
(parallel/mesh.py): dp/fsdp shard the batch (gradient all-reduce appears as
XLA collectives exactly where DDP's bucketed NCCL all-reduce sat), fsdp/tp
additionally shard params — the DDP wrap at trainer.py:71 has no analogue
because the *data layout* is the parallelism.

Kept reference semantics: construction order load-snapshot-then-wrap
(trainer.py:66-71 — here: restore before device placement), epoch loop with
eval pass (trainer.py:169-183), save cadence every ``save_every`` epochs,
missing snapshot => fresh start. Fixed: single global writer (B9),
step-granular resume (data iterator + RNG in the snapshot), reduced loss in
logs (B11).
"""

from __future__ import annotations

import dataclasses
import signal
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mingpt_distributed_tpu.config import (
    ConfigError,
    ExperimentConfig,
    GPTConfig,
    OptimizerConfig,
    TrainerConfig,
)
from mingpt_distributed_tpu.data.char_dataset import (
    CharView,
    IteratorState,
    ShardedBatchIterator,
)
from mingpt_distributed_tpu.models import gpt
from mingpt_distributed_tpu.parallel import mesh as mesh_lib
from mingpt_distributed_tpu.parallel import zero as zero_lib
from mingpt_distributed_tpu.training import checkpoint as ckpt_lib
from mingpt_distributed_tpu.training.durability import RetryPolicy
from mingpt_distributed_tpu.training.metrics import MetricsLogger
from mingpt_distributed_tpu.training.optimizer import lr_schedule, make_optimizer
from mingpt_distributed_tpu.telemetry import (
    SpanTracer,
    TelemetryServer,
    log_event,
    tree_bytes,
)

TrainState = Dict[str, Any]  # {"params", "opt_state", "step"}

# Exit code train.py returns after a preemption-triggered stop+snapshot:
# EX_TEMPFAIL, the conventional "transient, requeue me" code — cluster
# schedulers and wrapper scripts can restart the job, which then resumes
# from the just-committed snapshot.
REQUEUE_EXIT_CODE = 75

# canonical implementation lives with the other sharding rules
state_shardings = mesh_lib.state_shardings


def make_train_step(
    cfg: GPTConfig,
    optimizer: optax.GradientTransformation,
    mesh=None,
    grad_accum: int = 1,
    lr_fn=None,  # step -> learning rate, for the metrics line (SURVEY §5.5)
    zero_plan=None,  # parallel/zero.py ZeroPlan: dp-sharded weight update
):
    """forward+backward+update as one pure function of (state, batch, rng).

    ``grad_accum > 1`` splits the step's batch into that many micro-batches
    and accumulates gradients over a ``lax.scan`` before the single
    optimizer update — activation memory scales with B/grad_accum while the
    effective batch (and the loss/update semantics) stay the whole B.
    Micro-batch losses/grads are averaged with equal weight (the standard
    mean-of-means convention; exact whenever ignore_index masking is evenly
    distributed, and exactly equal to grad_accum=1 when no -1 targets).

    With a ``zero_plan`` the update phase runs ZeRO weight-update sharding
    (arXiv 2004.13336): grads are reduce-scattered over dp (the sharding
    constraint on the grads' update view turns the dp all-reduce into
    all-reduce+shard, which GSPMD fuses), clip/Adam/decay/lr run on the
    local 1/dp shard only, and the updated params are allgathered back to
    their canonical sharding by the output constraint. Composes with
    ``grad_accum`` unchanged — accumulation happens before the sharded
    update phase.
    """

    def loss_and_grads(params, x, y, rng, deterministic):
        def loss_fn(p):
            _, loss = gpt.forward(
                p, x, cfg, targets=y,
                rng=None if deterministic else rng,
                deterministic=deterministic,
                mesh=mesh,
                return_logits=False,  # loss-only: enables the chunked head
            )
            return loss

        return jax.value_and_grad(loss_fn)(params)

    def train_step(state: TrainState, batch, base_rng):
        x, y = batch
        rng = jax.random.fold_in(base_rng, state["step"])
        deterministic = (
            cfg.embd_pdrop == 0.0 and cfg.resid_pdrop == 0.0 and cfg.attn_pdrop == 0.0
        )

        if grad_accum > 1:
            b = x.shape[0]
            if b % grad_accum:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum={grad_accum}"
                )
            xs = x.reshape(grad_accum, b // grad_accum, *x.shape[1:])
            ys = y.reshape(grad_accum, b // grad_accum, *y.shape[1:])

            def acc(carry, mb):
                loss_sum, g_sum, i = carry
                x_mb, y_mb = mb
                mb_rng = jax.random.fold_in(rng, i)
                loss_i, g_i = loss_and_grads(
                    state["params"], x_mb, y_mb, mb_rng, deterministic
                )
                g_sum = jax.tree.map(
                    lambda a, bb: a + bb.astype(jnp.float32), g_sum, g_i
                )
                return (loss_sum + loss_i, g_sum, i + 1), None

            g0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state["params"]
            )
            (loss_sum, g_sum, _), _ = jax.lax.scan(
                acc,
                (jnp.zeros((), jnp.float32), g0, jnp.asarray(0, jnp.int32)),
                (xs, ys),
            )
            loss = loss_sum / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, g_sum)
        else:
            loss, grads = loss_and_grads(
                state["params"], x, y, rng, deterministic
            )

        if zero_plan is not None:
            # ZeRO update phase: shard grads+params into the update view,
            # step the optimizer on the local 1/dp shard, gather back.
            gview = zero_lib.constrain(
                zero_lib.update_view(grads, zero_plan), zero_plan
            )
            pview = zero_lib.constrain(
                zero_lib.update_view(state["params"], zero_plan), zero_plan
            )
            updates, new_opt = optimizer.update(
                gview, state["opt_state"], pview
            )
            # allgather happens here: from_view restores canonical shapes
            # and the step's out_shardings pin the canonical param layout
            new_params = zero_lib.from_view(
                optax.apply_updates(pview, updates), zero_plan
            )
        else:
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            new_params = optax.apply_updates(state["params"], updates)
        metrics = {
            "loss": loss,
            # pre-clip gradient norm (global: GSPMD psums sharded leaves)
            "grad_norm": optax.global_norm(grads),
            # post-clip/applied update norm — grad_norm alone can't show
            # whether clipping actually bit (flat-mode pad slots are zero
            # and contribute nothing)
            "update_norm": optax.global_norm(updates),
        }
        if lr_fn is not None:
            metrics["lr"] = lr_fn(state["step"])
        return (
            {"params": new_params, "opt_state": new_opt, "step": state["step"] + 1},
            metrics,
        )

    return train_step


def make_eval_step(cfg: GPTConfig, mesh=None):
    def eval_step(state: TrainState, batch):
        x, y = batch
        _, loss = gpt.forward(
            state["params"], x, cfg, targets=y, mesh=mesh,
            return_logits=False,
        )
        return loss

    return eval_step


class GPTTrainer:
    """Drives training of a GPT over a device mesh.

    Mirrors the reference constructor contract
    GPTTrainer(config, model, optimizer, train_dataset, test_dataset)
    (trainer.py:46-52) with the model/optimizer passed as *configs* — the
    model is data (a pytree), so the trainer owns materialisation, placement
    and restore.
    """

    def __init__(
        self,
        config: TrainerConfig,
        gpt_config: GPTConfig,
        optimizer_config: OptimizerConfig,
        train_dataset: CharView,
        test_dataset: Optional[CharView] = None,
        mesh=None,
        experiment_config: Optional[ExperimentConfig] = None,
    ):
        self.config = config
        self.gpt_config = gpt_config
        if config.debug_nans:
            jax.config.update("jax_debug_nans", True)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(config.mesh)
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_writer = self.process_index == 0  # B9 fix: GLOBAL process 0
        self.experiment_config = experiment_config

        # --- telemetry (ISSUE 5): spans + optional /metrics endpoint ------
        # Tracer enabled on the writer only (single-writer convention, same
        # as MetricsLogger); spans cover step dispatch, eval and snapshots.
        self.tracer = SpanTracer(enabled=self.is_writer)
        if config.spans_jsonl and self.is_writer:
            self.tracer.attach_jsonl(config.spans_jsonl)
        self.telemetry_server: Optional[TelemetryServer] = None
        metrics_registry = None
        if config.metrics_port and self.is_writer:
            from mingpt_distributed_tpu import telemetry

            metrics_registry = telemetry.get_registry()
            self.telemetry_server = TelemetryServer(
                metrics_registry, port=config.metrics_port
            )
            log_event(
                f"telemetry: serving /metrics and /healthz on "
                f"{self.telemetry_server.url()}",
                tracer=self.tracer,
            )

        batch_ways = int(
            np.prod([self.mesh.shape[a] for a in mesh_lib.BATCH_AXES])
        )
        if config.batch_size % batch_ways != 0:
            axes = "*".join(mesh_lib.BATCH_AXES)
            raise ValueError(
                f"trainer_config.batch_size={config.batch_size} must be "
                f"divisible by {axes}={batch_ways} (mesh "
                f"{dict(self.mesh.shape)})"
            )

        # ONE schedule object feeds both the optax chain and the metrics
        # line, so the logged lr is the applied lr by construction
        self._lr_fn = lr_schedule(optimizer_config)
        self.optimizer = make_optimizer(
            optimizer_config, config.grad_norm_clip, schedule=self._lr_fn
        )
        # How the global batch's ROWS split across processes is a property
        # of the batch SHARDING, not always of process_count: when a
        # non-batch axis spans hosts (e.g. sequence parallelism over DCN,
        # mesh sp across processes) every process addresses all rows and
        # must feed the full batch.
        self._feed_count, self._feed_index = self._data_feed_shards(
            config.batch_size, train_dataset.block_size
        )
        self.train_iter = ShardedBatchIterator(
            train_dataset,
            config.batch_size,
            shuffle=True,
            seed=config.seed,
            process_index=self._feed_index,
            process_count=self._feed_count,
        )
        self.test_iter = (
            ShardedBatchIterator(
                test_dataset,
                config.batch_size,
                shuffle=False,
                seed=config.seed,
                process_index=self._feed_index,
                process_count=self._feed_count,
            )
            if test_dataset is not None and len(test_dataset) >= config.batch_size
            else None
        )

        self.snapshot_path = config.snapshot_path or ckpt_lib.DEFAULT_SNAPSHOT_PATH
        # backend: .msgpack = single-blob (reference contract, host gather);
        # anything else = Orbax directory (sharded, collective, no gather)
        self.ckpt_backend = (
            "msgpack" if self.snapshot_path.endswith(".msgpack") else "orbax"
        )
        # durability: transient-I/O retry policy shared by save and load
        # (jitter seeded from config.seed for reproducible schedules)
        self._retry = RetryPolicy(
            attempts=config.io_retries,
            base_delay_s=config.io_retry_delay_s,
            seed=config.seed,
        )
        # preemption state: the SIGTERM/SIGINT handler flips
        # _stop_requested; the step loop honours it at the next boundary
        self._stop_requested = False
        self._stop_signal: Optional[int] = None
        self.preempted = False
        if config.async_save and self.ckpt_backend == "orbax":
            # refuse rather than silently run sync (VERDICT r4 #6): the
            # user asked for overlap they would not be getting
            raise ConfigError(
                "async_save=True only applies to the msgpack backend; Orbax "
                "sharded saves run synchronously (collective write). Set "
                "async_save=False, or use a .msgpack snapshot_path."
            )
        # --- ZeRO weight-update sharding over dp (opt-in, ISSUE 9) --------
        # The plan is static per (mesh, model): dp<=1 means the view would
        # be the identity, so the plan stays None and the step compiles the
        # exact replicated baseline program.
        self.zero_plan = None
        if config.zero_dp:
            if self.ckpt_backend == "orbax":
                # refuse rather than save the dp-local update view: the
                # Orbax backend writes device shards as-is, so a zero_dp
                # checkpoint would bake in this run's dp extent (and flat
                # padding) instead of the canonical resharding layout.
                raise ConfigError(
                    "zero_dp=True requires the msgpack backend (a "
                    ".msgpack snapshot_path): its save path canonicalises "
                    "the dp-sharded optimizer state so checkpoints restore "
                    "at any dp extent. Orbax would persist the view layout."
                )
            if int(self.mesh.shape["dp"]) > 1:
                params_shape = jax.eval_shape(
                    lambda: gpt.init(jax.random.key(config.seed), gpt_config)
                )
                self.zero_plan = zero_lib.make_plan(self.mesh, params_shape)
        self.base_rng = jax.random.key(config.seed)

        # --- abstract state + shardings, then materialise on-mesh ---------
        init_fn = lambda: self._fresh_state(jax.random.key(config.seed))
        state_shape = jax.eval_shape(init_fn)
        self.shardings = state_shardings(
            self.mesh, state_shape, zero_plan=self.zero_plan
        )
        self.batch_sharding = mesh_lib.batch_sharding(self.mesh)
        self.repl = NamedSharding(self.mesh, P())

        if self.ckpt_backend == "orbax":
            from mingpt_distributed_tpu.training import checkpoint_orbax

            restored = checkpoint_orbax.load_snapshot(
                self.snapshot_path,
                state_shape["params"],
                state_shape["opt_state"],
                shardings=self.shardings,
                retry=self._retry,
            )
        else:
            # Checkpoints store the opt state in CANONICAL layout (original
            # leaf shapes, no dp padding) regardless of zero_dp — restore
            # into the canonical skeleton, then re-view for THIS mesh's
            # plan. That is the whole reshard-on-restore mechanism: a
            # snapshot written at dp=4 localises cleanly at dp=2 or dp=1.
            opt_like = state_shape["opt_state"]
            if self.zero_plan is not None:
                opt_like = zero_lib.canonical_opt_shape(
                    opt_like, self.zero_plan
                )
            restored = ckpt_lib.load_snapshot(
                self.snapshot_path,
                state_shape["params"],
                opt_like,
                retry=self._retry,
            )
            if restored is not None and self.zero_plan is not None:
                restored = dataclasses.replace(
                    restored,
                    opt_state=zero_lib.localize_opt_state(
                        restored.opt_state, self.zero_plan
                    ),
                )
        if restored is None:
            if self.is_writer:
                log_event("Snapshot not found. Training model from scratch",
                          tracer=self.tracer)
            self.state = jax.jit(init_fn, out_shardings=self.shardings)()
            self.start_epoch = 0
        else:
            host_state = {
                "params": restored.params,
                "opt_state": restored.opt_state,
                "step": jnp.asarray(restored.step, dtype=jnp.int32),
            }
            placed = jax.tree.map(
                lambda x, s: (
                    x  # orbax restores already placed with the right sharding
                    if getattr(x, "sharding", None) == s
                    else jax.make_array_from_callback(
                        np.shape(x), s, lambda idx: np.asarray(x)[idx]
                    )
                ),
                host_state,
                self.shardings,
            )
            # Launder the restored buffers through one compiled (undonated)
            # copy so the donated train step only ever sees executable-owned
            # buffers: donating externally-created arrays into an executable
            # deserialised from the persistent compilation cache corrupts
            # the heap on the CPU backend (resume-then-train segfault; the
            # fresh-init path was immune because jit(init_fn) outputs are
            # executable-owned).
            self.state = jax.jit(
                lambda s: jax.tree.map(jnp.copy, s),
                out_shardings=self.shardings,
            )(placed)
            self.start_epoch = restored.epoch
            self.train_iter.state = IteratorState.from_dict(
                restored.data_state
            ) if restored.data_state else self.train_iter.state
            if restored.prng is not None:
                # continue the saved RNG stream, not config.seed's
                self.base_rng = jax.random.wrap_key_data(
                    jnp.asarray(restored.prng)
                )
            if self.is_writer:
                log_event(
                    f"Resuming training from snapshot at epoch "
                    f"{restored.epoch}, step {restored.step}",
                    tracer=self.tracer,
                    epoch=restored.epoch, step=restored.step,
                )

        # --- compiled steps ----------------------------------------------
        self._train_step = jax.jit(
            make_train_step(gpt_config, self.optimizer, self.mesh,
                            grad_accum=config.grad_accum_steps,
                            lr_fn=self._lr_fn,
                            zero_plan=self.zero_plan),
            in_shardings=(self.shardings, (self.batch_sharding,) * 2, self.repl),
            out_shardings=(self.shardings, self.repl),
            donate_argnums=(0,),
        )
        self._eval_step = jax.jit(
            make_eval_step(gpt_config, self.mesh),
            in_shardings=(self.shardings, (self.batch_sharding,) * 2),
            out_shardings=self.repl,
        )

        # performance attribution (ISSUE 13): set by register_attrib()
        self._attrib = None
        self._attrib_clock = None
        self._attrib_variant = ""

        self.metrics = MetricsLogger(
            gpt_config,
            jsonl_path=config.metrics_jsonl if self.is_writer else None,
            tensorboard_dir=(
                config.tensorboard_dir if self.is_writer else None
            ),
            n_chips=len(jax.devices()),
            enabled=self.is_writer,
            registry=metrics_registry,
        )
        if self.is_writer:
            log_event(gpt.model_size_report(self.state["params"], gpt_config),
                      tracer=self.tracer)

    # ------------------------------------------------------------------
    def _fresh_state(self, rng) -> TrainState:
        params = gpt.init(rng, self.gpt_config)
        if self.zero_plan is not None:
            # moments live in the update view (flat-mode leaves padded +
            # flattened) so they can be physically 1/dp under the plan's
            # shardings; Adam init on pad zeros is zeros, so the view is
            # exactly the localised canonical state
            opt_state = self.optimizer.init(
                zero_lib.update_view(params, self.zero_plan)
            )
        else:
            opt_state = self.optimizer.init(params)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": jnp.asarray(0, dtype=jnp.int32),
        }

    # -- performance attribution (ISSUE 13) ----------------------------
    def register_attrib(self, ledger, clock, hbm=None) -> None:
        """Register the compiled train step with a ProgramLedger.

        AOT-lowers ``self._train_step`` against abstract state/batch
        avals — donation binds at execution, not lowering, so no live
        buffer is consumed and the backend executable cache makes the
        first real dispatch a cache hit. Family ``train_step``, variant
        ``zero`` (dp-sharded update, ISSUE 9) or ``dense``. Per-step
        host-visible wall time then feeds ``observe_call`` from the
        train loop through the SAME injected clock — deterministic under
        a virtual clock, never a library ``time.*`` read.

        With an :class:`HBMLedger` the resident training state is
        accounted too: params at canonical size, optimizer moments at
        the zero-plan's per-device extent (``opt_moment_bytes``).
        """
        self._attrib = ledger
        self._attrib_clock = clock
        self._attrib_variant = (
            "zero" if self.zero_plan is not None else "dense")
        abstract = lambda x: jax.ShapeDtypeStruct(
            jnp.shape(x), jnp.result_type(x))
        state_abs = jax.tree.map(abstract, self.state)
        block = self.train_iter.view.block_size
        tok = jax.ShapeDtypeStruct(
            (self.config.batch_size, block), jnp.int32)
        rng_abs = jax.eval_shape(lambda: self.base_rng)
        ledger.register_aot(
            "train_step", self._train_step,
            (state_abs, (tok, tok), rng_abs),
            clock, variant=self._attrib_variant)
        if hbm is not None:
            params_abs = state_abs["params"]
            hbm.account("params", tree_bytes(params_abs))
            hbm.account("opt_state", zero_lib.opt_moment_bytes(
                params_abs, self.zero_plan))

    def audit_contracts(self) -> dict:
        """Audit contract (ISSUE 15) for the ``train_step`` family
        ``register_attrib`` registers. On a one-device mesh the lowered
        step must contain no collectives at all; on a real mesh the data/
        tensor/zero parallel forms all appear (psum grads, zero's
        reduce-scatter + all-gather, megatron gathers), so every reduce-
        family op is declared. Donation is ``donate_argnums=(0,)`` over
        the whole train state: the executable must alias at least one
        output per params leaf (``donated_min`` — opt-state leaves alias
        too, but their count depends on the optimizer/zero layout, so the
        params floor is the invariant worth pinning)."""
        n_dev = int(np.prod(self.mesh.devices.shape))
        allowed = (() if n_dev == 1 else
                   ("all-gather", "all-reduce", "collective-permute",
                    "reduce-scatter"))
        return {
            "train_step": {
                "allowed_collectives": allowed,
                "donated_min": len(jax.tree.leaves(self.state["params"])),
            },
        }

    def _data_feed_shards(self, global_batch: int, seq_len: int):
        """(n_shards, my_shard) for host data feeding.

        Derived from ``batch_sharding``'s device->index map: the rows this
        process's local devices address. Pure dp/fsdp/ep over hosts gives
        the usual equal contiguous split; a mesh whose batch rows are NOT
        cleanly process-partitioned (sp spanning hosts, or exotic layouts)
        degrades to every host feeding the full batch, which
        make_array_from_process_local_data accepts (host data may match the
        global shape).
        """
        if self.process_count == 1:
            return 1, 0
        rows: set = set()
        m = mesh_lib.batch_sharding(self.mesh).devices_indices_map(
            (global_batch, seq_len)
        )
        for d, idx in m.items():
            if d.process_index == jax.process_index():
                rows.update(range(*idx[0].indices(global_batch)))
        my = sorted(rows)
        n_rows = len(my)
        contiguous = my == list(range(my[0], my[0] + n_rows))
        if (
            n_rows == global_batch
            or not contiguous
            or global_batch % n_rows
            or my[0] % n_rows
        ):
            return 1, 0  # feed the full batch on every host
        return global_batch // n_rows, my[0] // n_rows

    def _put_batch(self, xy: Tuple[np.ndarray, np.ndarray]):
        """Per-host local shard -> global device array under batch sharding."""
        x, y = xy
        gshape = (x.shape[0] * self._feed_count, x.shape[1])
        if self.process_count == 1:
            put = lambda a: jax.device_put(a, self.batch_sharding)
        else:
            put = lambda a: jax.make_array_from_process_local_data(
                self.batch_sharding, a, gshape
            )
        return put(x), put(y)

    @property
    def step(self) -> int:
        return int(jax.device_get(self.state["step"]))

    # -- preemption ----------------------------------------------------
    def request_stop(self, signum: Optional[int] = None) -> None:
        """Ask the loop to stop at the next step boundary (callable from a
        signal handler or programmatically). Idempotent."""
        self._stop_requested = True
        self._stop_signal = signum

    def _on_signal(self, signum, frame) -> None:
        if self._stop_requested and signum == signal.SIGINT:
            # second Ctrl-C: the user really means now
            raise KeyboardInterrupt
        name = signal.Signals(signum).name
        if self.is_writer:
            log_event(
                f"[trainer] {name} received — stopping at the next step "
                f"boundary, snapshotting, then exiting with code "
                f"{REQUEUE_EXIT_CODE} (requeue)",
                tracer=self.tracer, signal=name,
            )
        self.request_stop(signum)

    def _install_signal_handlers(self):
        """SIGTERM (the preemption notice TPU spot VMs deliver) and SIGINT
        request a graceful stop+snapshot. Returns the handlers to restore,
        or None when not applicable (off, or not the main thread —
        python only delivers signals to the main thread)."""
        if not self.config.handle_signals:
            return None
        if threading.current_thread() is not threading.main_thread():
            return None
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, self._on_signal)
        return prev

    # ------------------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        """Epoch loop (reference train(), trainer.py:169-183): resume at
        start_epoch, train, periodic eval + snapshot. Returns final metrics.

        Preemption-safe: a SIGTERM/SIGINT during the loop stops at the
        next step boundary, snapshots, joins any async save, and sets
        ``self.preempted`` so the entry point can exit with
        REQUEUE_EXIT_CODE instead of losing the run.
        """
        prev_handlers = self._install_signal_handlers()
        try:
            return self._train_loop()
        finally:
            if prev_handlers is not None:
                for sig, h in prev_handlers.items():
                    signal.signal(sig, h)

    def _train_loop(self) -> Dict[str, Any]:
        cfg = self.config
        last: Dict[str, Any] = {}
        tokens_per_step = cfg.batch_size * self.train_iter.view.block_size
        stop = False
        # host-side step mirror: no per-batch D2H sync (the reference's
        # per-batch loss.item() stall, SURVEY §3.1, is what this avoids).
        # prev_metrics bounds the async pipeline to 2 in-flight steps: the
        # host waits on step N-1 while N executes — free on TPU (compute
        # overlaps), and it keeps per-device dispatch queues from skewing
        # past the collective-rendezvous timeout on oversubscribed hosts.
        py_step = self.step
        prev_metrics = None
        for epoch in range(self.start_epoch, cfg.max_epochs):
            # the prefetch thread advances the iterator's internal state
            # ahead of consumption; `consumed` is the truth for resume
            consumed = self.train_iter.state.step_in_epoch
            source = self.train_iter.epoch_batches()
            if cfg.prefetch > 0:
                from mingpt_distributed_tpu.data.prefetch import PrefetchIterator

                source = PrefetchIterator(source, depth=cfg.prefetch)
            for xy in source:
                batch = self._put_batch(xy)
                # the span measures host-visible step time: dispatch of step
                # N plus the wait on step N-1 (the two-in-flight cap below)
                ta0 = (self._attrib_clock()
                       if self._attrib is not None else 0.0)
                with self.tracer.span("train.step", step=py_step + 1):
                    self.state, m = self._train_step(
                        self.state, batch, self.base_rng
                    )
                    if prev_metrics is not None:
                        jax.block_until_ready(prev_metrics)
                    prev_metrics = m
                if self._attrib is not None:
                    # host-visible step time (dispatch N + wait on N-1),
                    # read on the injected attribution clock
                    self._attrib.observe_call(
                        "train_step", self._attrib_clock() - ta0,
                        variant=self._attrib_variant)
                py_step = step = py_step + 1
                consumed += 1
                # jax.profiler trace window (SURVEY §5.1: the reference has
                # no profiler at all; xplane output feeds Perfetto/XProf)
                if cfg.profile_dir and self.is_writer:
                    if step == cfg.profile_steps[0]:
                        jax.profiler.start_trace(cfg.profile_dir)
                        self._tracing = True
                    elif step == cfg.profile_steps[1] and getattr(
                        self, "_tracing", False
                    ):
                        jax.block_until_ready(m)
                        jax.profiler.stop_trace()
                        self._tracing = False
                        log_event(
                            f"profiler trace written to {cfg.profile_dir}",
                            tracer=self.tracer, step=step,
                        )
                if step % cfg.log_every == 0 or (
                    cfg.max_steps and step >= cfg.max_steps
                ):
                    scalars = {k: float(jax.device_get(v)) for k, v in m.items()}
                    scalars["epoch"] = epoch
                    last = self.metrics.log_step(
                        step, tokens_per_step, self.train_iter.view.block_size,
                        scalars,
                    )
                if self._stop_requested:
                    # preemption: get off the chip at this step boundary —
                    # snapshot below, skip eval, requeue-friendly exit
                    self.preempted = True
                    stop = True
                if cfg.max_steps and step >= cfg.max_steps:
                    stop = True
                if stop:
                    break
            if stop:
                # stop the producer thread BEFORE touching iterator state:
                # it mutates train_iter.state ahead of consumption, and a
                # write landing after the re-sync below would persist a data
                # position beyond what was trained (resume would skip batches)
                if cfg.prefetch > 0:
                    source.close()
                # re-sync iterator state to the batches actually trained on
                # (prefetch ran ahead); resume continues at exactly here
                self.train_iter.state = IteratorState(
                    epoch=epoch, step_in_epoch=consumed,
                    seed=self.train_iter.state.seed,
                )
            epoch_done = epoch + (0 if stop else 1)
            if self.test_iter is not None and not self.preempted and (
                stop or (epoch + 1) % cfg.eval_every == 0
            ):
                last["eval_loss"] = self.evaluate()
                if self.is_writer:
                    log_event(
                        f"epoch {epoch} | eval_loss {last['eval_loss']:.4f}",
                        tracer=self.tracer, epoch=epoch,
                    )
            if stop or (epoch + 1) % cfg.save_every == 0:
                self.save_snapshot(epoch_done)
            if stop:
                break
        self._join_pending_save()  # async_save: flush before returning
        return last

    def _join_pending_save(self) -> None:
        """Wait for an in-flight async snapshot write; re-raise its failure
        (a swallowed write error would mean silently resuming from a stale
        checkpoint after the next restart)."""
        t = getattr(self, "_save_thread", None)
        if t is not None:
            t.join()
            self._save_thread = None
        exc = getattr(self, "_save_exc", None)
        if exc is not None:
            self._save_exc = None
            raise RuntimeError(
                f"async snapshot write to {self.snapshot_path} failed"
            ) from exc

    def evaluate(self) -> float:
        """Mean loss over the eval set.

        Losses stay on device; the loop only *blocks* on the step two
        iterations back (the train loop's two-in-flight cap) instead of
        fetching every batch — on a pod a per-batch device_get costs a full
        host round-trip per batch and stalls the dispatch pipeline
        (VERDICT r2 weak #7). Values are fetched once at the end.
        """
        assert self.test_iter is not None
        losses = []
        self.test_iter.state = IteratorState(seed=self.config.seed)
        with self.tracer.span("train.eval"):
            for i, xy in enumerate(self.test_iter.epoch_batches()):
                if self.config.eval_batches and i >= self.config.eval_batches:
                    break
                losses.append(self._eval_step(self.state, self._put_batch(xy)))
                if len(losses) >= 2:
                    jax.block_until_ready(losses[-2])
            return float(np.mean([float(v) for v in jax.device_get(losses)]))

    def save_snapshot(self, epoch: int) -> None:
        """Single-writer (global process 0 — the B9 fix) snapshot.

        ALL processes must call this (it is called from train() on every
        process): with fsdp/tp sharding some shards live on other hosts, so
        the state is first gathered to every host with a collective
        (process_allgather); only process 0 then writes.
        """
        with self.tracer.span("train.snapshot", epoch=epoch):
            self._save_snapshot(epoch)

    def _save_snapshot(self, epoch: int) -> None:
        common = dict(
            step=self.step,
            epoch=epoch,
            prng=np.asarray(jax.random.key_data(self.base_rng)),
            data_state=self.train_iter.state.to_dict(),
            config=(
                self.experiment_config.to_dict() if self.experiment_config else {}
            ),
        )
        if self.ckpt_backend == "orbax":
            # collective sharded save: every process writes its shards
            from mingpt_distributed_tpu.training import checkpoint_orbax

            checkpoint_orbax.save_snapshot(
                self.snapshot_path,
                ckpt_lib.Snapshot(
                    params=self.state["params"],
                    opt_state=self.state["opt_state"],
                    **common,
                ),
                retry=self._retry,
            )
        else:
            if self.process_count > 1:
                # refuse the doomed gather: allgathering a pod-scale state
                # to every host OOMs long after the run invested hours —
                # fail at save time with the fix in hand (VERDICT r4 #6)
                state_mb = sum(
                    x.size * x.dtype.itemsize
                    for x in jax.tree.leaves(
                        {"params": self.state["params"],
                         "opt_state": self.state["opt_state"]}
                    )
                ) / 2**20
                limit_mb = self.config.msgpack_gather_limit_mb
                if state_mb > limit_mb:
                    raise RuntimeError(
                        f"multi-host msgpack save would allgather "
                        f"{state_mb:.0f} MB of state to every host "
                        f"(limit {limit_mb} MB). Use the Orbax backend — a "
                        f"snapshot_path without the .msgpack suffix — for "
                        f"sharded collective writes with no gather, or "
                        f"raise trainer_config.msgpack_gather_limit_mb if "
                        f"your hosts have the RAM."
                    )
                from jax.experimental import multihost_utils

                params = multihost_utils.process_allgather(
                    self.state["params"], tiled=True
                )
                opt_state = multihost_utils.process_allgather(
                    self.state["opt_state"], tiled=True
                )
            else:
                params = self.state["params"]
                opt_state = self.state["opt_state"]
            if self.zero_plan is not None:
                # snapshots always store the CANONICAL layout (original
                # shapes, no dp padding) so they restore at any dp extent
                opt_state = zero_lib.canonical_opt_state(
                    jax.device_get(opt_state), self.zero_plan
                )
            # shard the checkpoint data objects with the update shards:
            # per-shard writes/digests keep save cost ~per-host-state
            n_shards = self.zero_plan.dp if self.zero_plan is not None else 1
            if not self.is_writer:
                return
            if self.config.async_save:
                # overlap serialization + IO (the slow part for object
                # stores) with training. The host copy happens HERE, before
                # the thread starts: the device buffers are donated to the
                # next step and would be invalidated under the writer.
                host_snap = ckpt_lib.Snapshot(
                    params=jax.device_get(params),
                    opt_state=jax.device_get(opt_state),
                    **common,
                )
                self._join_pending_save()  # re-raises a prior failed write
                import threading

                path, step = self.snapshot_path, self.step
                keep, retry = self.config.keep_snapshots, self._retry

                def _write():
                    try:
                        ckpt_lib.save_snapshot(
                            path, host_snap, keep=keep, retry=retry,
                            shards=n_shards,
                        )
                        log_event(
                            f"Snapshot saved to {path} "
                            f"(epoch {epoch}, step {step}, msgpack, async)",
                            tracer=self.tracer, epoch=epoch, step=step,
                        )
                    except BaseException as e:  # re-raised at next join
                        self._save_exc = e

                self._save_thread = threading.Thread(target=_write)
                self._save_thread.start()
                return
            else:
                ckpt_lib.save_snapshot(
                    self.snapshot_path,
                    ckpt_lib.Snapshot(
                        params=params, opt_state=opt_state, **common
                    ),
                    keep=self.config.keep_snapshots,
                    retry=self._retry,
                    shards=n_shards,
                )
        if self.is_writer:
            log_event(
                f"Snapshot saved to {self.snapshot_path} "
                f"(epoch {epoch}, step {self.step}, {self.ckpt_backend})",
                tracer=self.tracer, epoch=epoch, step=self.step,
            )

    def close(self) -> None:
        """Release telemetry resources: metric sinks, the span JSONL, and
        the /metrics endpoint (idempotent)."""
        self.metrics.close()
        self.tracer.close()
        if self.telemetry_server is not None:
            self.telemetry_server.close()
            self.telemetry_server = None
