"""Optimizer factory: AdamW with the GPT-2 decay/no-decay parameter partition.

Replaces the reference's ``create_optimizer`` (/root/reference/mingpt/model.py:
62-122), which walks torch named_modules to split parameters into a decayed
group (Linear/attention projection weights) and an un-decayed group (all
biases, LayerNorm weights, token/positional embeddings), asserts the split is
a partition of all parameters (model.py:97-104), and builds a two-group AdamW
(model.py:107-121) with the GPT-3 hyperparameters (lr 3e-4, wd 0.1, betas
(0.9, 0.95) — OptimizerConfig, model.py:54-59).

TPU-native mechanism: there are no modules — the partition is a *pytree mask*
derived from parameter names, fed to ``optax.add_decayed_weights``. The
partition-completeness assert survives as ``decay_mask``'s refusal to classify
an unknown parameter name. Gradient clipping (the reference does it in the
trainer, trainer.py:129, with the deprecated-API bug B11) is folded into the
same optax chain as ``clip_by_global_norm``, so one fused update kernel does
clip -> Adam -> decay -> lr.

The LR schedule implements the warmup+cosine lore the reference README records
(README.md:93,125) but the reference never built (its LR is constant).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax

from mingpt_distributed_tpu.config import OptimizerConfig
from mingpt_distributed_tpu.utils.pytree import leaf_name

# Parameter-name -> weight-decay classification, mirroring the reference's
# module-walk rules (model.py:78-93):
#   decay:    every matmul weight (Linear / attention projections / LM head)
#   no-decay: every bias, every norm scale/bias, token + positional embeddings
_DECAY_NAMES = frozenset(
    {"wq", "wk", "wv", "wo", "w_fc", "w_proj", "w_gate", "w_up", "w_down",
     "head", "w_router", "w_e1", "w_e2", "w_eg"}  # MoE router/experts are matmuls
)
_NO_DECAY_NAMES = frozenset(
    {
        "wte", "wpe",  # embeddings (reference: Embedding + pos_embedding no-decay)
        "bq", "bk", "bv", "bo", "b_fc", "b_proj",  # biases
        "ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias",
        "lnf_scale", "lnf_bias",
    }
)


def decay_mask(params: Any) -> Any:
    """Boolean pytree: True where weight decay applies.

    Raises on any parameter name that matches neither rule set — the pytree
    analogue of the reference's partition-completeness asserts
    (model.py:97-104): no parameter may be silently un-classified.
    """

    def classify(path, leaf):
        name = leaf_name(path)
        if name in _DECAY_NAMES:
            return True
        if name in _NO_DECAY_NAMES:
            return False
        raise ValueError(
            f"parameter {jax.tree_util.keystr(path)!r} not covered by the "
            f"decay/no-decay partition rules"
        )

    return jax.tree_util.tree_map_with_path(classify, params)


def lr_schedule(cfg: OptimizerConfig) -> Callable[[Any], Any]:
    """constant (reference behavior) or linear-warmup + cosine decay."""
    if cfg.schedule == "constant":
        if cfg.warmup_steps:
            return optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
        return optax.constant_schedule(cfg.learning_rate)
    if cfg.schedule == "cosine":
        if cfg.total_steps is None:
            raise ValueError("cosine schedule needs optimizer_config.total_steps")
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=cfg.total_steps,
            end_value=cfg.learning_rate * cfg.min_lr_ratio,
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def make_optimizer(
    cfg: OptimizerConfig,
    grad_norm_clip: Optional[float] = None,
    schedule: Optional[Callable[[Any], Any]] = None,
) -> optax.GradientTransformation:
    """clip -> scale_by_adam -> masked weight decay -> lr, as one chain.

    ``schedule`` lets the caller share ONE schedule object between the
    optimizer and metrics reporting, so the logged lr is the applied lr by
    construction (defaults to ``lr_schedule(cfg)``).
    """
    parts = []
    if grad_norm_clip is not None and grad_norm_clip > 0:
        parts.append(optax.clip_by_global_norm(grad_norm_clip))
    parts += [
        optax.scale_by_adam(b1=cfg.betas[0], b2=cfg.betas[1], eps=cfg.eps),
        optax.add_decayed_weights(cfg.weight_decay, mask=decay_mask),
        optax.scale_by_learning_rate(schedule or lr_schedule(cfg)),
    ]
    return optax.chain(*parts)
