"""Metrics / observability (SURVEY §5.5 upgrade).

The reference logs an unreduced per-rank loss via print every 100 batches
(/root/reference/mingpt/trainer.py:144-147) and nothing else; its README
self-deprecates the approach (README.md:74). Here: structured per-step
metrics — loss (already a global mean: the batch axis spans the whole mesh),
grad norm, LR, tokens/sec/chip and MFU from the 6ND flop model — emitted from
process 0 only, to stdout and optionally a JSONL file (pluggable sink).

ISSUE 5: the roofline peak tables, the ``RateWindow`` helper, and the
JSONL schema now live in ``mingpt_distributed_tpu.telemetry`` (re-exported
here for back-compat), and every scalar the logger prints is also set on
``mingpt_train_*`` gauges in a :class:`~..telemetry.MetricsRegistry` —
pass the process registry (``telemetry.get_registry()``) to expose them
on the same ``/metrics`` page as the serving metrics.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.telemetry import (  # noqa: F401 — re-exports
    PEAK_FLOPS,
    PEAK_HBM_BYTES,
    JsonlEventSink,
    MetricsRegistry,
    RateWindow,
    log_event,
    peak_flops_per_chip,
    peak_hbm_bytes_per_chip,
)

_GAUGE_SAFE_RE = re.compile(r"[^a-zA-Z0-9_]")


def flops_per_token(cfg: GPTConfig, seq_len: Optional[int] = None) -> float:
    """Training FLOPs/token: 6*N_matmul + attention term 12*L*d*T
    (the 6ND model with the quadratic-attention correction)."""
    t = seq_len or cfg.block_size
    d, l, v = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    ffn = int(cfg.ffn_mult * d)
    kv = cfg.kv_heads * cfg.head_dim
    per_layer = d * (d + 2 * kv) + d * d  # qkv + out proj
    per_layer += (3 if cfg.swiglu else 2) * d * ffn
    n_matmul = l * per_layer + d * v  # + lm head (embeddings are gathers)
    attn = 12 * l * d * t  # 2 score+value matmuls, fwd+bwd (6x), * d * T
    return 6 * n_matmul + attn


class MetricsLogger:
    """stdout + optional JSONL + optional TensorBoard sinks + registry
    gauges; rate/MFU computed over log windows (SURVEY §5.5's prescription
    — the reference logs per-rank unreduced loss via print only,
    trainer.py:144-147).

    ``registry`` defaults to a fresh private one (test isolation, the
    prometheus_client idiom); entry points pass
    ``telemetry.get_registry()`` so training gauges land on the shared
    scrape page. The JSONL sink writes the versioned
    ``mingpt-telemetry/1`` schema with ``kind: "train_step"`` and the
    per-step scalars flat at the top level (pre-existing consumers that
    read ``rec["loss"]``/``rec["step"]`` are unaffected).
    """

    def __init__(
        self,
        cfg: GPTConfig,
        *,
        jsonl_path: Optional[str] = None,
        tensorboard_dir: Optional[str] = None,
        n_chips: int = 1,
        enabled: bool = True,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.cfg = cfg
        self.n_chips = max(n_chips, 1)
        self.enabled = enabled
        self.registry = registry if registry is not None else MetricsRegistry()
        self._jsonl: Optional[JsonlEventSink] = None
        if enabled and jsonl_path:
            self._jsonl = JsonlEventSink(jsonl_path)
        self._tb = None
        if enabled and tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=tensorboard_dir)
            except Exception as e:  # optional dep — degrade to other sinks
                log_event(f"tensorboard sink unavailable ({e}); continuing")
        self._rate = RateWindow()
        self._peak = peak_flops_per_chip()
        self._step_gauge = self.registry.gauge(
            "mingpt_train_step", help="last logged training step")
        self._gauges: Dict[str, Any] = {}
        # Pre-register the headline families so the scrape page advertises
        # them from process start — MFU in particular may never be observed
        # on chips missing from the peak table (e.g. the CPU test mesh).
        for key, help_ in (
            ("loss", "training loss (global mean over the mesh batch axis)"),
            ("mfu", "model FLOPs utilization vs the chip's roofline peak"),
        ):
            self._gauges[key] = self.registry.gauge(
                f"mingpt_train_{key}", help=help_)

    def _gauge(self, key: str):
        g = self._gauges.get(key)
        if g is None:
            safe = _GAUGE_SAFE_RE.sub("_", key)
            g = self.registry.gauge(
                f"mingpt_train_{safe}", help=f"training scalar {key!r}")
            self._gauges[key] = g
        return g

    def log_step(
        self, step: int, tokens_per_step: int, seq_len: int, scalars: Dict[str, Any]
    ) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"step": step}
        rec.update({k: float(v) for k, v in scalars.items()})
        steps_per_sec = self._rate.observe(step)
        if steps_per_sec is not None:
            tps = tokens_per_step * steps_per_sec
            rec["tokens_per_sec"] = tps
            rec["tokens_per_sec_per_chip"] = tps / self.n_chips
            flops = flops_per_token(self.cfg, seq_len) * tps / self.n_chips
            rec["flops_per_chip"] = flops
            if self._peak:
                rec["mfu"] = flops / self._peak
        self._step_gauge.set(step)
        for k, v in rec.items():
            if k != "step":
                self._gauge(k).set(v)
        if self.enabled:
            parts = [f"step {step}"] + [
                f"{k} {v:.4g}" for k, v in rec.items() if k != "step"
            ]
            log_event(" | ".join(parts), step=step)
            if self._jsonl:
                self._jsonl.write("train_step", dict(rec))
            if self._tb:
                for k, v in rec.items():
                    if k != "step":
                        self._tb.add_scalar(k, v, step)
        return rec

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb:
            self._tb.close()
            self._tb = None
