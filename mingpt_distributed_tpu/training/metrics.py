"""Metrics / observability (SURVEY §5.5 upgrade).

The reference logs an unreduced per-rank loss via print every 100 batches
(/root/reference/mingpt/trainer.py:144-147) and nothing else; its README
self-deprecates the approach (README.md:74). Here: structured per-step
metrics — loss (already a global mean: the batch axis spans the whole mesh),
grad norm, LR, tokens/sec/chip and MFU from the 6ND flop model — emitted from
process 0 only, to stdout and optionally a JSONL file (pluggable sink).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional, TextIO

import jax

from mingpt_distributed_tpu.config import GPTConfig

# Peak dense bf16 FLOP/s per chip, for MFU. Public numbers.
PEAK_FLOPS: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
}


# Peak HBM bandwidth per chip (bytes/s), for memory-bound rooflines
# (KV-cached decode streams the whole parameter set per token, so its
# ceiling is bandwidth, not FLOPs). Public numbers.
PEAK_HBM_BYTES: dict[str, float] = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,  # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,  # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,  # v6e (Trillium)
}


class RateWindow:
    """Windowed rate of a monotonically increasing marker (steps, tokens).

    ``observe(marker)`` returns the marker's change per second since the
    previous call, or None on the first call / when the marker did not
    advance. Shared plumbing between the training MetricsLogger (steps/sec
    → tokens/sec/MFU) and the serving metrics (tokens/sec, serving/metrics
    .py) so both report rates over the same kind of log window.
    """

    def __init__(self) -> None:
        self._last: Optional[tuple[float, float]] = None

    def observe(self, marker: float, now: Optional[float] = None) -> Optional[float]:
        if now is None:
            now = time.perf_counter()
        rate = None
        if self._last is not None:
            last_t, last_m = self._last
            if marker > last_m and now > last_t:
                rate = (marker - last_m) / (now - last_t)
        self._last = (now, marker)
        return rate


def _chip_lookup(table: dict[str, float]) -> Optional[float]:
    # longest-prefix-wins by dict order: "TPU v5 lite" is listed before
    # "TPU v5" in both tables, so v5e doesn't read the v5p row
    kind = jax.devices()[0].device_kind
    for name, val in table.items():
        if kind.startswith(name):
            return val
    return None


def peak_flops_per_chip() -> Optional[float]:
    return _chip_lookup(PEAK_FLOPS)


def peak_hbm_bytes_per_chip() -> Optional[float]:
    return _chip_lookup(PEAK_HBM_BYTES)


def flops_per_token(cfg: GPTConfig, seq_len: Optional[int] = None) -> float:
    """Training FLOPs/token: 6*N_matmul + attention term 12*L*d*T
    (the 6ND model with the quadratic-attention correction)."""
    t = seq_len or cfg.block_size
    d, l, v = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    ffn = int(cfg.ffn_mult * d)
    kv = cfg.kv_heads * cfg.head_dim
    per_layer = d * (d + 2 * kv) + d * d  # qkv + out proj
    per_layer += (3 if cfg.swiglu else 2) * d * ffn
    n_matmul = l * per_layer + d * v  # + lm head (embeddings are gathers)
    attn = 12 * l * d * t  # 2 score+value matmuls, fwd+bwd (6x), * d * T
    return 6 * n_matmul + attn


class MetricsLogger:
    """stdout + optional JSONL + optional TensorBoard sinks; rate/MFU
    computed over log windows (SURVEY §5.5's prescription — the reference
    logs per-rank unreduced loss via print only, trainer.py:144-147)."""

    def __init__(
        self,
        cfg: GPTConfig,
        *,
        jsonl_path: Optional[str] = None,
        tensorboard_dir: Optional[str] = None,
        n_chips: int = 1,
        enabled: bool = True,
    ):
        self.cfg = cfg
        self.n_chips = max(n_chips, 1)
        self.enabled = enabled
        self._jsonl: Optional[TextIO] = None
        if enabled and jsonl_path:
            self._jsonl = open(jsonl_path, "a")
        self._tb = None
        if enabled and tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=tensorboard_dir)
            except Exception as e:  # optional dep — degrade to other sinks
                print(f"tensorboard sink unavailable ({e}); continuing")
        self._rate = RateWindow()
        self._peak = peak_flops_per_chip()

    def log_step(
        self, step: int, tokens_per_step: int, seq_len: int, scalars: Dict[str, Any]
    ) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"step": step}
        rec.update({k: float(v) for k, v in scalars.items()})
        steps_per_sec = self._rate.observe(step)
        if steps_per_sec is not None:
            tps = tokens_per_step * steps_per_sec
            rec["tokens_per_sec"] = tps
            rec["tokens_per_sec_per_chip"] = tps / self.n_chips
            flops = flops_per_token(self.cfg, seq_len) * tps / self.n_chips
            rec["flops_per_chip"] = flops
            if self._peak:
                rec["mfu"] = flops / self._peak
        if self.enabled:
            parts = [f"step {step}"] + [
                f"{k} {v:.4g}" for k, v in rec.items() if k != "step"
            ]
            print(" | ".join(parts), flush=True)
            if self._jsonl:
                self._jsonl.write(json.dumps(rec) + "\n")
                self._jsonl.flush()
            if self._tb:
                for k, v in rec.items():
                    if k != "step":
                        self._tb.add_scalar(k, v, step)
        return rec

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb:
            self._tb.close()
            self._tb = None
