"""Checkpoint / resume: step-granular snapshots to local disk, S3 or GCS.

Re-design of the reference's snapshot machinery (SURVEY §3.4/§5.4):
ModelSnapshot (/root/reference/mingpt/trainer.py:33-37), torch.save to disk or
BytesIO->boto3 S3 (trainer.py:83-95,149-167), fsspec read + try-load-else-fresh
(trainer.py:97-116). Kept: the same public semantics — a single snapshot path
(any fsspec URL: local, ``s3://``, ``gs://``), "missing snapshot = train from
scratch", the wrapper-agnostic schema. Fixed / upgraded:

* **single writer** — only process 0 writes (the reference gated on
  *local* rank 0, so every node raced on one S3 key — bug B9);
* **step-granular resume** — snapshot carries step, epoch, PRNG key and the
  data-iterator state, not just an epoch counter (the reference loses
  mid-epoch progress, sampler position and RNG — SURVEY §5.4 "not saved");
* **no pickle** — arrays go through flax.serialization msgpack (the
  reference's torch.load of an untrusted path executes pickle);
* atomic local writes (tmp + rename) so a killed job can't leave a torn
  snapshot behind;
* **durable, crash-consistent saves** (training/durability.py): every
  save writes a step-suffixed data object and then commits it via a small
  JSON manifest (``<path>.manifest.json``: ``latest`` pointer +
  per-checkpoint SHA-256 digest + step), keeping the last K checkpoints.
  All fsspec I/O retries transient errors with exponential backoff, and
  restore verifies the digest — falling back to the previous good
  checkpoint on a torn/truncated/bit-flipped blob instead of crashing
  (or loading garbage).

The serialised schema is the public contract (ModelSnapshot analogue):
``{version, step, epoch, prng, data_state, config, state: {params, opt_state}}``.
A legacy single blob at the bare ``path`` (the pre-manifest layout) still
restores; new saves always go through the manifest.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from mingpt_distributed_tpu.training import durability
from mingpt_distributed_tpu.training.durability import (
    RetryPolicy,
    SnapshotIntegrityError,
)

SNAPSHOT_VERSION = 1
#: payload version of one shard of a sharded snapshot (ISSUE 9): same
#: schema as v1 plus ``shard``/``n_shards`` framing; every leaf is
#: flattened and split into n_shards contiguous chunks, meta fields
#: (prng/data_state/config) ride in shard 0 only.
SHARDED_SNAPSHOT_VERSION = 2
DEFAULT_SNAPSHOT_PATH = "gpt_snapshot.msgpack"  # reference default: gpt_snapshot.pt
DEFAULT_KEEP = 3  # checkpoints retained in the manifest (keep-last-K)


@dataclass
class Snapshot:
    """In-memory snapshot (the reference's ModelSnapshot, trainer.py:33-37,
    extended to step granularity)."""

    params: Any
    opt_state: Any
    step: int = 0
    epoch: int = 0
    prng: Optional[np.ndarray] = None
    data_state: dict = field(default_factory=dict)
    config: dict = field(default_factory=dict)


def _to_host(tree: Any) -> Any:
    """Fully-addressable host copy of a (possibly sharded) pytree."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def _chunk_state(sd: Any, i: int, n: int) -> Any:
    """Shard ``i``'s slice of a state dict: every leaf flattened and
    contiguously split into ``n`` near-equal chunks (0-d leaves land
    wholly in shard 0; np.array_split pads nothing)."""
    if isinstance(sd, dict):
        return {k: _chunk_state(v, i, n) for k, v in sd.items()}
    return np.array_split(np.asarray(sd).reshape(-1), n)[i]


def _assemble_state(skel_sd: Any, shard_sds: list, label: str) -> Any:
    """Inverse of ``_chunk_state``: concatenate every leaf's chunks across
    the shard payloads and reshape against the skeleton state dict."""
    if isinstance(skel_sd, dict):
        try:
            return {
                k: _assemble_state(skel_sd[k], [s[k] for s in shard_sds], label)
                for k in skel_sd
            }
        except KeyError as e:
            raise ValueError(
                f"sharded snapshot {label} is missing key {e} expected by "
                f"the current config — refusing to restore"
            ) from None
    flat = np.concatenate([np.asarray(s).reshape(-1) for s in shard_sds])
    return flat.reshape(tuple(np.shape(skel_sd)))


def save_snapshot(
    path: str,
    snap: Snapshot,
    keep: int = DEFAULT_KEEP,
    retry: Optional[RetryPolicy] = None,
    shards: int = 1,
) -> None:
    """Serialise and durably commit. Call only from the single writer
    (process 0).

    The write protocol (durability.commit_blob/commit_shards): the data
    objects land at step-suffixed keys nothing references yet (local keys
    additionally use tmp+rename, the reference's atomicity, now with a
    digest), then the manifest PUT commits them as a unit. A crash or
    injected fault anywhere in between leaves the previous manifest — and
    every checkpoint it points at — fully intact. Transient fsspec errors
    retry with backoff + jitter.

    ``shards > 1`` (manifest schema v2) splits the state into that many
    data objects with per-shard digests — ZeRO runs pass their dp extent
    so write amplification tracks per-host state. The *contents* are
    layout-independent (each leaf contiguously chunked), so any shard
    count restores against any other; the shard count is a property of
    the write, not of the checkpoint.
    """
    state = {
        "params": _to_host(snap.params),
        "opt_state": _to_host(snap.opt_state),
    }
    if shards <= 1:
        payload = {
            "version": SNAPSHOT_VERSION,
            "step": snap.step,
            "epoch": snap.epoch,
            "prng": None if snap.prng is None else np.asarray(snap.prng),
            "data_state": json.dumps(snap.data_state),
            "config": json.dumps(snap.config),
            "state": state,
        }
        blob = serialization.to_bytes(payload)
        durability.commit_blob(
            path, blob, step=snap.step, epoch=snap.epoch, keep=keep,
            policy=retry,
        )
        return
    state_sd = serialization.to_state_dict(state)
    blobs = []
    for i in range(shards):
        payload = {
            "version": SHARDED_SNAPSHOT_VERSION,
            "shard": i,
            "n_shards": shards,
            "step": snap.step,
            "epoch": snap.epoch,
            # meta rides in shard 0 only — it is tiny and restoring it
            # twice would be ambiguity, not redundancy
            "prng": (
                np.asarray(snap.prng)
                if i == 0 and snap.prng is not None else None
            ),
            "data_state": json.dumps(snap.data_state) if i == 0 else "",
            "config": json.dumps(snap.config) if i == 0 else "",
            "state": _chunk_state(state_sd, i, shards),
        }
        blobs.append(serialization.to_bytes(payload))
    durability.commit_shards(
        path, blobs, step=snap.step, epoch=snap.epoch, keep=keep, policy=retry
    )


def load_snapshot(
    path: str,
    params_like: Any,
    opt_state_like: Any = None,
    retry: Optional[RetryPolicy] = None,
) -> Optional[Snapshot]:
    """Try to load; None = no snapshot, train from scratch (the reference's
    FileNotFoundError branch, trainer.py:103-107).

    Restore path: read the manifest, walk newest → oldest, return the
    first checkpoint whose SHA-256 matches its committed digest and whose
    payload deserialises — a torn/truncated latest falls back to the
    previous good checkpoint. No manifest falls back to the legacy single
    blob at the bare ``path``. Only *missing* (durability.classify_io_error
    — FileNotFoundError or any ENOENT-carrying OSError, regardless of
    fsspec backend) means fresh start; transient I/O retries then raises,
    so a blip can never be mistaken for "no snapshot" and let a later save
    overwrite the only good state.

    ``params_like`` / ``opt_state_like`` supply the target pytree structure
    (fresh init or eval_shape) the serialised arrays are poured into —
    shape/dtype mismatch raises rather than silently mistraining.
    ``opt_state_like=None`` skips optimizer state (inference-only restore);
    the returned Snapshot then has ``opt_state=None``.
    """
    manifest = durability.load_manifest(path, retry)
    if manifest is not None and manifest.entries:
        blobs, entry = durability.read_verified_shards(path, manifest, retry)
        if entry.shards is None:
            payload = _restore_payload(blobs[0], source=entry.key)
        else:
            payload = _restore_sharded(
                blobs, entry, params_like, opt_state_like
            )
    else:
        # legacy pre-manifest layout: one blob at the bare path
        try:
            blob = durability.read_bytes(path, retry)
        except BaseException as e:  # noqa: BLE001 — classified, not blanket
            if durability.is_missing_error(e):
                return None
            raise
        payload = _restore_payload(blob, source=path)
    params = _owned(serialization.from_state_dict(
        _abstract_to_zeros(params_like), payload["state"]["params"]
    ))
    _check_shapes(params_like, params, "params")
    opt_state = None
    if opt_state_like is not None:
        opt_state = _owned(serialization.from_state_dict(
            _abstract_to_zeros(opt_state_like), payload["state"]["opt_state"]
        ))
        _check_shapes(opt_state_like, opt_state, "opt_state")
    prng = payload["prng"]
    if prng is not None:
        prng = np.array(prng)
    return Snapshot(
        params=params,
        opt_state=opt_state,
        step=int(payload["step"]),
        epoch=int(payload["epoch"]),
        prng=None if prng is None or np.ndim(prng) == 0 else np.asarray(prng),
        data_state=json.loads(payload["data_state"]) if payload["data_state"] else {},
        config=json.loads(payload["config"]) if payload["config"] else {},
    )


def _owned(tree: Any) -> Any:
    """Deep-copy restored leaves into memory the caller owns.

    msgpack_restore hands back READ-ONLY numpy views into the serialised
    blob. jax's CPU backend zero-copy-adopts immutable aligned numpy
    arrays on device_put — and the trainer then DONATES the restored
    state to the compiled step, so XLA would write into (and recycle)
    heap memory owned by the blob's bytes object: nondeterministic
    corruption/segfaults on resume. Owned writable copies force a real
    device buffer and also let the (much larger) blob be GC'd instead of
    being pinned by views."""
    return jax.tree.map(np.array, tree)


def _restore_payload(
    blob: bytes, source: str, expected: int = SNAPSHOT_VERSION
) -> dict:
    """msgpack bytes -> payload dict, with version gate and a corruption
    error that names the offending object."""
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception as e:
        raise SnapshotIntegrityError(
            f"snapshot blob {source} is corrupt (msgpack decode failed): {e}"
        ) from e
    if payload["version"] != expected:
        raise ValueError(
            f"snapshot version {payload['version']} != {expected}"
        )
    return payload


def _restore_sharded(
    blobs: list, entry, params_like: Any, opt_state_like: Any
) -> dict:
    """Shard payloads (already digest-verified) -> one v1-shaped payload
    with fully assembled state sections. Works for ANY saved shard count:
    the chunking is layout-independent, so this is where a dp=4 checkpoint
    reshards onto a dp=2 or dp=1 run."""
    payloads = [
        _restore_payload(
            blob, source=entry.shards[i].key,
            expected=SHARDED_SNAPSHOT_VERSION,
        )
        for i, blob in enumerate(blobs)
    ]
    payloads.sort(key=lambda p: int(p["shard"]))
    n = len(payloads)
    if [int(p["shard"]) for p in payloads] != list(range(n)) or any(
        int(p["n_shards"]) != n for p in payloads
    ):
        raise SnapshotIntegrityError(
            f"sharded snapshot at step {entry.step} has inconsistent shard "
            f"framing: got shards "
            f"{[(int(p['shard']), int(p['n_shards'])) for p in payloads]}"
        )
    head = payloads[0]
    state_sds = [p["state"] for p in payloads]
    params_skel = serialization.to_state_dict(_abstract_to_zeros(params_like))
    state = {
        "params": _assemble_state(
            params_skel, [s["params"] for s in state_sds], "params"
        ),
        "opt_state": None,
    }
    if opt_state_like is not None:
        opt_skel = serialization.to_state_dict(
            _abstract_to_zeros(opt_state_like)
        )
        state["opt_state"] = _assemble_state(
            opt_skel, [s["opt_state"] for s in state_sds], "opt_state"
        )
    return {
        "version": SNAPSHOT_VERSION,
        "step": head["step"],
        "epoch": head["epoch"],
        "prng": head["prng"],
        "data_state": head["data_state"],
        "config": head["config"],
        "state": state,
    }


def _check_shapes(expected: Any, restored: Any, label: str) -> None:
    """Refuse shape/dtype drift between the current config's state and the
    snapshot — e.g. a vocab change with a stale snapshot_path would otherwise
    silently mistrain (flax from_bytes does not validate leaf shapes)."""

    def check(path, exp, got):
        eshape = tuple(getattr(exp, "shape", ()) or ())
        gshape = tuple(np.shape(got))
        if eshape != gshape:
            raise ValueError(
                f"snapshot {label} leaf {jax.tree_util.keystr(path)} has "
                f"shape {gshape}, but the current config expects {eshape} — "
                f"refusing to restore (did the dataset/model config change "
                f"under an old snapshot_path?)"
            )

    jax.tree_util.tree_map_with_path(check, expected, restored)


def _abstract_to_zeros(tree: Any) -> Any:
    """Accept concrete arrays or ShapeDtypeStructs as the target skeleton."""

    def conv(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return np.zeros(x.shape, x.dtype)
        return x

    return jax.tree.map(conv, tree)


def restore_inference_params(path: str, gpt_cfg) -> Optional[Snapshot]:
    """Restore a train.py snapshot for inference (params only, no optimizer
    state): the backend dispatch sample.py and serve.py share. ``.msgpack``
    = single blob (this module); anything else = Orbax directory (a sharded
    checkpoint is not an openable file). Returns None when no snapshot
    exists at ``path``."""
    from mingpt_distributed_tpu.models import gpt

    params_shape = jax.eval_shape(
        lambda k: gpt.init(k, gpt_cfg), jax.random.key(0)
    )
    if path.endswith(".msgpack"):
        return load_snapshot(path, params_shape)
    from mingpt_distributed_tpu.training import checkpoint_orbax

    return checkpoint_orbax.load_snapshot(path, params_shape)
