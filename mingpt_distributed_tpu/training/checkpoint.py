"""Checkpoint / resume: step-granular snapshots to local disk, S3 or GCS.

Re-design of the reference's snapshot machinery (SURVEY §3.4/§5.4):
ModelSnapshot (/root/reference/mingpt/trainer.py:33-37), torch.save to disk or
BytesIO->boto3 S3 (trainer.py:83-95,149-167), fsspec read + try-load-else-fresh
(trainer.py:97-116). Kept: the same public semantics — a single snapshot path
(any fsspec URL: local, ``s3://``, ``gs://``), "missing snapshot = train from
scratch", the wrapper-agnostic schema. Fixed / upgraded:

* **single writer** — only process 0 writes (the reference gated on
  *local* rank 0, so every node raced on one S3 key — bug B9);
* **step-granular resume** — snapshot carries step, epoch, PRNG key and the
  data-iterator state, not just an epoch counter (the reference loses
  mid-epoch progress, sampler position and RNG — SURVEY §5.4 "not saved");
* **no pickle** — arrays go through flax.serialization msgpack (the
  reference's torch.load of an untrusted path executes pickle);
* atomic local writes (tmp + rename) so a killed job can't leave a torn
  snapshot behind.

The on-disk schema is the public contract (ModelSnapshot analogue):
``{version, step, epoch, prng, data_state, config, state: {params, opt_state}}``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import fsspec
import jax
import numpy as np
from flax import serialization

SNAPSHOT_VERSION = 1
DEFAULT_SNAPSHOT_PATH = "gpt_snapshot.msgpack"  # reference default: gpt_snapshot.pt


@dataclass
class Snapshot:
    """In-memory snapshot (the reference's ModelSnapshot, trainer.py:33-37,
    extended to step granularity)."""

    params: Any
    opt_state: Any
    step: int = 0
    epoch: int = 0
    prng: Optional[np.ndarray] = None
    data_state: dict = field(default_factory=dict)
    config: dict = field(default_factory=dict)


def _to_host(tree: Any) -> Any:
    """Fully-addressable host copy of a (possibly sharded) pytree."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def save_snapshot(path: str, snap: Snapshot) -> None:
    """Serialise and write. Call only from the single writer (process 0)."""
    payload = {
        "version": SNAPSHOT_VERSION,
        "step": snap.step,
        "epoch": snap.epoch,
        "prng": None if snap.prng is None else np.asarray(snap.prng),
        "data_state": json.dumps(snap.data_state),
        "config": json.dumps(snap.config),
        "state": {
            "params": _to_host(snap.params),
            "opt_state": _to_host(snap.opt_state),
        },
    }
    blob = serialization.to_bytes(payload)
    if "://" in path:
        # object stores (s3://, gs://) — fsspec transport, the reference's
        # boto3 upload path (trainer.py:93-95) generalised
        with fsspec.open(path, "wb") as f:
            f.write(blob)
    else:
        # local: atomic tmp+rename so resume never sees a torn file
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)


def load_snapshot(
    path: str, params_like: Any, opt_state_like: Any = None
) -> Optional[Snapshot]:
    """Try to load; None = no snapshot, train from scratch (the reference's
    FileNotFoundError branch, trainer.py:103-107).

    ``params_like`` / ``opt_state_like`` supply the target pytree structure
    (fresh init or eval_shape) the serialised arrays are poured into —
    shape/dtype mismatch raises rather than silently mistraining.
    ``opt_state_like=None`` skips optimizer state (inference-only restore);
    the returned Snapshot then has ``opt_state=None``.
    """
    try:
        with fsspec.open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        # only a *missing* snapshot means fresh start; transient I/O or
        # permission errors must propagate, or a later save would overwrite
        # a good snapshot with fresh-init state
        return None
    payload = serialization.msgpack_restore(blob)
    if payload["version"] != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot version {payload['version']} != {SNAPSHOT_VERSION}"
        )
    params = serialization.from_state_dict(
        _abstract_to_zeros(params_like), payload["state"]["params"]
    )
    _check_shapes(params_like, params, "params")
    opt_state = None
    if opt_state_like is not None:
        opt_state = serialization.from_state_dict(
            _abstract_to_zeros(opt_state_like), payload["state"]["opt_state"]
        )
        _check_shapes(opt_state_like, opt_state, "opt_state")
    prng = payload["prng"]
    return Snapshot(
        params=params,
        opt_state=opt_state,
        step=int(payload["step"]),
        epoch=int(payload["epoch"]),
        prng=None if prng is None or np.ndim(prng) == 0 else np.asarray(prng),
        data_state=json.loads(payload["data_state"]) if payload["data_state"] else {},
        config=json.loads(payload["config"]) if payload["config"] else {},
    )


def _check_shapes(expected: Any, restored: Any, label: str) -> None:
    """Refuse shape/dtype drift between the current config's state and the
    snapshot — e.g. a vocab change with a stale snapshot_path would otherwise
    silently mistrain (flax from_bytes does not validate leaf shapes)."""

    def check(path, exp, got):
        eshape = tuple(getattr(exp, "shape", ()) or ())
        gshape = tuple(np.shape(got))
        if eshape != gshape:
            raise ValueError(
                f"snapshot {label} leaf {jax.tree_util.keystr(path)} has "
                f"shape {gshape}, but the current config expects {eshape} — "
                f"refusing to restore (did the dataset/model config change "
                f"under an old snapshot_path?)"
            )

    jax.tree_util.tree_map_with_path(check, expected, restored)


def _abstract_to_zeros(tree: Any) -> Any:
    """Accept concrete arrays or ShapeDtypeStructs as the target skeleton."""

    def conv(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return np.zeros(x.shape, x.dtype)
        return x

    return jax.tree.map(conv, tree)


def restore_inference_params(path: str, gpt_cfg) -> Optional[Snapshot]:
    """Restore a train.py snapshot for inference (params only, no optimizer
    state): the backend dispatch sample.py and serve.py share. ``.msgpack``
    = single blob (this module); anything else = Orbax directory (a sharded
    checkpoint is not an openable file). Returns None when no snapshot
    exists at ``path``."""
    from mingpt_distributed_tpu.models import gpt

    params_shape = jax.eval_shape(
        lambda k: gpt.init(k, gpt_cfg), jax.random.key(0)
    )
    if path.endswith(".msgpack"):
        return load_snapshot(path, params_shape)
    from mingpt_distributed_tpu.training import checkpoint_orbax

    return checkpoint_orbax.load_snapshot(path, params_shape)
