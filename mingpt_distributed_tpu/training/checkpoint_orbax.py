"""Orbax checkpoint backend — sharded, no host gather.

The msgpack backend (training/checkpoint.py) keeps the reference's
single-blob snapshot contract but gathers the whole state to host 0 — fine
up to a few GB, wrong for GPT-2 XL/Llama-scale sharded state (BASELINE
configs #4/#5). This backend writes each host's shards directly via Orbax
(OCDBT/tensorstore under the hood) and restores arrays *already placed* on
the mesh with their target shardings — no host-memory spike, no broadcast.

Same public semantics as the msgpack backend: one snapshot location,
try-load-else-fresh, metadata {step, epoch, prng, data_state, config}
alongside the state. Unlike msgpack, save/restore here are collective:
EVERY process must call them (orbax coordinates the multi-host commit with a
final atomic rename by process 0).

Backend selection (training/trainer.py): paths ending in ``.msgpack`` use
the msgpack backend; other paths (directories) use Orbax.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from mingpt_distributed_tpu.training import durability
from mingpt_distributed_tpu.training.checkpoint import Snapshot
from mingpt_distributed_tpu.training.durability import RetryPolicy


def _abs(path: str) -> str:
    return path if "://" in path else os.path.abspath(path)


def save_snapshot(
    path: str, snap: Snapshot, retry: RetryPolicy | None = None
) -> None:
    """Collective sharded save (call from ALL processes).

    Atomicity is Orbax's own commit protocol (write to a tmp dir, final
    rename by process 0). Transient-I/O retries apply only in
    single-process runs: on a pod, hosts retrying a *collective* save
    independently would desynchronise the rendezvous (one host re-enters
    while the rest moved on) — there the error propagates and the whole
    job requeues instead."""
    meta = {
        "step": int(snap.step),
        "epoch": int(snap.epoch),
        "prng": None if snap.prng is None else np.asarray(snap.prng).tolist(),
        "data_state": snap.data_state,
        "config": snap.config,
    }
    state = {"params": snap.params, "opt_state": snap.opt_state}

    def _save():
        with ocp.Checkpointer(
            ocp.CompositeCheckpointHandler()
        ) as ckptr:
            ckptr.save(
                _abs(path),
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta),
                ),
                force=True,  # overwrite-in-place cadence, like the reference
            )

    if jax.process_count() == 1:
        durability.with_retries(_save, retry, op=f"orbax save {path}")
    else:
        _save()


def load_snapshot(
    path: str,
    params_like: Any,
    opt_state_like: Any = None,
    shardings: Any = None,
    retry: Optional[RetryPolicy] = None,
) -> Optional[Snapshot]:
    """Collective restore. ``params_like``/``opt_state_like`` are abstract
    trees (eval_shape); ``shardings`` (same structure, {"params","opt_state"})
    places restored arrays directly on the mesh.

    Missing-vs-transient classification is shared with the msgpack backend
    (durability.classify_io_error): only a genuinely missing checkpoint
    means fresh start — fsspec/tensorstore backends that surface missing
    objects as bare ENOENT OSErrors get the same verdict, and transient
    errors retry with backoff instead of fresh-starting over a blip."""
    apath = _abs(path)
    if "://" not in apath and not os.path.isdir(apath):
        return None

    def as_abstract(tree, shard_tree):
        def one(x, s):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        if shard_tree is None:
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
            )
        return jax.tree.map(one, tree, shard_tree)

    abstract_state = {
        "params": as_abstract(
            params_like, None if shardings is None else shardings["params"]
        ),
    }
    if opt_state_like is not None:
        abstract_state["opt_state"] = as_abstract(
            opt_state_like,
            None if shardings is None else shardings["opt_state"],
        )
    def _restore():
        with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
            return ckptr.restore(
                apath,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    meta=ocp.args.JsonRestore(),
                ),
            )

    try:
        restored = durability.with_retries(
            _restore, retry, op=f"orbax restore {apath}"
        )
    except BaseException as e:  # noqa: BLE001 — classified, not blanket
        if durability.is_missing_error(e):
            return None
        raise
    meta = restored["meta"]
    state = restored["state"]
    prng = meta.get("prng")
    return Snapshot(
        params=state["params"],
        opt_state=state.get("opt_state"),
        step=int(meta["step"]),
        epoch=int(meta["epoch"]),
        prng=None if prng is None else np.asarray(prng, dtype=np.uint32),
        data_state=meta.get("data_state") or {},
        config=meta.get("config") or {},
    )
