"""Durable I/O primitives for checkpointing: error classification, retry
with exponential backoff + jitter, SHA-256 digests, and the commit
manifest that turns a set of snapshot objects into a crash-consistent
checkpoint history.

Production TPU training treats preemption and storage flakiness as the
steady state (PAPERS.md: "Scalable Training of Language Models using JAX
pjit and TPUv4"). The failure modes this module is built around:

* **transient I/O** — an object-store PUT/GET times out or resets; the
  only correct reaction is backoff + retry, not killing a multi-hour run;
* **missing object** — fsspec backends surface "no such key" as
  ``FileNotFoundError`` *or* other ``OSError`` subclasses depending on
  backend; missing must be classified in ONE place so "fresh start" and
  "retry" never get confused (a transient error mistaken for missing
  would let a later save overwrite the only good state);
* **torn / corrupt blobs** — a writer killed mid-PUT, or a store that
  returns truncated bytes. Every committed object carries a SHA-256
  digest in the manifest; restore verifies before trusting.

The commit protocol (``Manifest``): data objects are written under
step-suffixed keys that nothing references yet, then a small JSON
manifest — ``latest`` pointer + per-checkpoint digest/step — is written
last as the single commit point. A crash between the two leaves the
previous manifest (and every object it references) fully intact.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import fsspec

from mingpt_distributed_tpu.telemetry import log_event

MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"

# -- error classification ---------------------------------------------------

MISSING = "missing"
TRANSIENT = "transient"
PERMANENT = "permanent"

# errno values that mean "the object is not there" rather than "the store
# hiccuped" — ENOENT is the POSIX spelling; some fsspec backends raise a
# bare OSError carrying it instead of FileNotFoundError.
_MISSING_ERRNOS = {errno.ENOENT}
# errors that retrying cannot fix: bad credentials, a directory where a
# file was expected, read-only stores.
_PERMANENT_OSERRORS = (
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def classify_io_error(exc: BaseException) -> str:
    """One shared verdict for every fsspec/OS error the checkpoint layer
    sees: ``missing`` | ``transient`` | ``permanent``.

    Used by both the retry loop (retry only ``transient``) and
    ``load_snapshot`` ("fresh start" only on ``missing``) so the two can
    never disagree about what a given exception means.
    """
    if isinstance(exc, _PERMANENT_OSERRORS):
        return PERMANENT
    if isinstance(exc, FileNotFoundError):
        return MISSING
    if isinstance(exc, OSError):
        if exc.errno in _MISSING_ERRNOS:
            return MISSING
        # covers TimeoutError, ConnectionError, BlockingIOError, and the
        # anonymous OSErrors object-store backends raise on flaky transport
        return TRANSIENT
    return PERMANENT


def is_missing_error(exc: BaseException) -> bool:
    return classify_io_error(exc) == MISSING


class SnapshotIntegrityError(RuntimeError):
    """Every checkpoint referenced by the manifest failed digest or
    deserialisation checks — restoring would load corrupt state, and
    fresh-starting would let the next save overwrite the evidence."""


# -- retry ------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic-seedable jitter.

    ``sleep`` is injectable so tests (and the fault harness) run with zero
    wall-clock delay; ``seed`` pins the jitter sequence.
    """

    attempts: int = 4
    base_delay_s: float = 0.5
    max_delay_s: float = 8.0
    multiplier: float = 2.0
    jitter: float = 0.25          # fraction of the delay randomised away
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def delays(self):
        rng = random.Random(self.seed)
        d = self.base_delay_s
        for _ in range(max(self.attempts - 1, 0)):
            yield d * (1.0 - self.jitter * rng.random())
            d = min(d * self.multiplier, self.max_delay_s)


#: zero-sleep policy for tests and the --selftest-faults smoke
NO_WAIT = RetryPolicy(attempts=4, base_delay_s=0.0, seed=0, sleep=lambda _: None)


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    op: str = "io",
) -> Any:
    """Run ``fn``; retry transient failures per ``policy``.

    ``missing`` and ``permanent`` errors raise immediately (retrying a 404
    or a permission error only delays the inevitable); the last transient
    error raises once attempts are exhausted.
    """
    policy = policy or RetryPolicy()
    delays = policy.delays()
    attempt = 1
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            verdict = classify_io_error(e)
            if verdict != TRANSIENT:
                raise
            try:
                delay = next(delays)
            except StopIteration:
                raise e
            log_event(
                f"[durability] transient {op} error "
                f"(attempt {attempt}/{policy.attempts}): {e!r}; "
                f"retrying in {delay:.2f}s",
                op=op, attempt=attempt,
            )
            policy.sleep(delay)
            attempt += 1


# -- digests ----------------------------------------------------------------


def sha256_hex(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


# -- byte transport (retry-wrapped, atomic where the backend allows) --------


def _is_local(path: str) -> bool:
    return "://" not in path


def write_bytes(
    path: str, blob: bytes, policy: Optional[RetryPolicy] = None
) -> None:
    """Write ``blob`` to ``path`` with retries.

    Local paths write tmp+rename so a killed writer can never leave a torn
    file at the final name. Remote (``://``) paths write the key directly —
    the manifest commit protocol is what makes that safe: an uncommitted
    key is invisible to readers.
    """
    if _is_local(path):
        def put():
            tmp = path + ".tmp"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
    else:
        def put():
            fs, p = fsspec.core.url_to_fs(path)
            with fs.open(p, "wb") as f:
                f.write(blob)
    with_retries(put, policy, op=f"write {path}")


def read_bytes(path: str, policy: Optional[RetryPolicy] = None) -> bytes:
    """Read ``path`` fully, with retries on transient errors. ``missing``
    raises FileNotFoundError-family immediately (callers map it to their
    own semantics — fresh start, or fall back to a previous checkpoint)."""
    def get():
        fs, p = fsspec.core.url_to_fs(path)
        with fs.open(p, "rb") as f:
            return f.read()
    return with_retries(get, policy, op=f"read {path}")


def delete_quiet(path: str) -> None:
    """Best-effort delete (checkpoint rotation): never raises — a
    leftover rotated-out object is garbage, not a correctness problem."""
    try:
        fs, p = fsspec.core.url_to_fs(path)
        fs.rm(p)
    except BaseException:  # noqa: BLE001
        pass


# -- the commit manifest ----------------------------------------------------


@dataclass
class ManifestEntry:
    key: str          # object key, relative to the manifest's directory
    step: int
    epoch: int
    sha256: str
    size: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass
class Manifest:
    """``latest`` pointer + ordered checkpoint history, committed as one
    small JSON PUT. Entries are oldest → newest; restore walks newest →
    oldest until a digest-verified checkpoint loads."""

    entries: List[ManifestEntry] = field(default_factory=list)

    @property
    def latest(self) -> Optional[ManifestEntry]:
        return self.entries[-1] if self.entries else None

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": MANIFEST_VERSION,
                "latest": self.latest.key if self.latest else None,
                "checkpoints": [e.to_dict() for e in self.entries],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "Manifest":
        raw = json.loads(text)
        if raw.get("version") != MANIFEST_VERSION:
            raise ValueError(
                f"manifest version {raw.get('version')} != {MANIFEST_VERSION}"
            )
        return cls(
            entries=[ManifestEntry(**e) for e in raw.get("checkpoints", [])]
        )


def manifest_path(snapshot_path: str) -> str:
    return snapshot_path + MANIFEST_SUFFIX


def object_key(snapshot_path: str, step: int) -> str:
    """Step-suffixed data key next to ``snapshot_path`` (never the bare
    path itself — the bare path is reserved for legacy single-blob
    snapshots, which restore still reads)."""
    return f"{snapshot_path}.step-{step:08d}"


def _sibling(snapshot_path: str, key: str) -> str:
    """Resolve a manifest-relative key next to the snapshot path."""
    head = snapshot_path.rsplit("/", 1)[0] if "/" in snapshot_path else "."
    return f"{head}/{key}"


def load_manifest(
    snapshot_path: str, policy: Optional[RetryPolicy] = None
) -> Optional[Manifest]:
    """None = no manifest (legacy layout or fresh run); transient errors
    retry then raise — they must never be mistaken for 'fresh start'."""
    try:
        text = read_bytes(manifest_path(snapshot_path), policy)
    except BaseException as e:  # noqa: BLE001
        if is_missing_error(e):
            return None
        raise
    return Manifest.from_json(text.decode("utf-8"))


def commit_blob(
    snapshot_path: str,
    blob: bytes,
    step: int,
    epoch: int,
    keep: int = 3,
    policy: Optional[RetryPolicy] = None,
) -> ManifestEntry:
    """The durable-write protocol: data object first (uncommitted key),
    manifest second (the commit point), rotation last (best effort).

    Returns the committed entry. ``keep`` bounds the history; the
    rotated-out objects are deleted only AFTER the new manifest no longer
    references them, so no reader can race into a dangling pointer.
    """
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    key_path = object_key(snapshot_path, step)
    write_bytes(key_path, blob, policy)

    manifest = load_manifest(snapshot_path, policy) or Manifest()
    entry = ManifestEntry(
        key=key_path.rsplit("/", 1)[-1],
        step=int(step),
        epoch=int(epoch),
        sha256=sha256_hex(blob),
        size=len(blob),
    )
    # re-saving the same step replaces that entry (e.g. a retried run that
    # stopped at the same boundary) instead of growing duplicate keys
    manifest.entries = [e for e in manifest.entries if e.step != entry.step]
    manifest.entries.append(entry)
    dropped = manifest.entries[:-keep]
    manifest.entries = manifest.entries[-keep:]
    write_bytes(
        manifest_path(snapshot_path), manifest.to_json().encode(), policy
    )
    for old in dropped:
        delete_quiet(_sibling(snapshot_path, old.key))
    return entry


def read_verified(
    snapshot_path: str,
    manifest: Manifest,
    policy: Optional[RetryPolicy] = None,
) -> Tuple[bytes, ManifestEntry]:
    """Walk the manifest newest → oldest; return the first blob whose
    SHA-256 matches its committed digest. A digest-mismatched (torn,
    truncated, bit-flipped) or unreadable blob is reported and skipped —
    restore falls back to the previous good checkpoint instead of
    crashing or, worse, loading garbage into the optimizer."""
    failures = []
    for entry in reversed(manifest.entries):
        path = _sibling(snapshot_path, entry.key)
        try:
            blob = read_bytes(path, policy)
        except BaseException as e:  # noqa: BLE001
            if classify_io_error(e) == PERMANENT:
                raise
            failures.append(f"{entry.key}: unreadable ({e!r})")
            continue
        digest = sha256_hex(blob)
        if digest != entry.sha256:
            failures.append(
                f"{entry.key}: digest mismatch "
                f"(manifest {entry.sha256[:12]}…, got {digest[:12]}…, "
                f"{len(blob)}/{entry.size} bytes)"
            )
            continue
        if failures:
            log_event(
                "[durability] fell back to checkpoint "
                f"step {entry.step} after: " + "; ".join(failures),
                step=entry.step,
            )
        return blob, entry
    raise SnapshotIntegrityError(
        f"no checkpoint in {manifest_path(snapshot_path)} passed "
        f"verification: " + "; ".join(failures)
    )
