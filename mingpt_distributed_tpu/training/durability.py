"""Durable I/O primitives for checkpointing: error classification, retry
with exponential backoff + jitter, SHA-256 digests, and the commit
manifest that turns a set of snapshot objects into a crash-consistent
checkpoint history.

Production TPU training treats preemption and storage flakiness as the
steady state (PAPERS.md: "Scalable Training of Language Models using JAX
pjit and TPUv4"). The failure modes this module is built around:

* **transient I/O** — an object-store PUT/GET times out or resets; the
  only correct reaction is backoff + retry, not killing a multi-hour run;
* **missing object** — fsspec backends surface "no such key" as
  ``FileNotFoundError`` *or* other ``OSError`` subclasses depending on
  backend; missing must be classified in ONE place so "fresh start" and
  "retry" never get confused (a transient error mistaken for missing
  would let a later save overwrite the only good state);
* **torn / corrupt blobs** — a writer killed mid-PUT, or a store that
  returns truncated bytes. Every committed object carries a SHA-256
  digest in the manifest; restore verifies before trusting.

The commit protocol (``Manifest``): data objects are written under
step-suffixed keys that nothing references yet, then a small JSON
manifest — ``latest`` pointer + per-checkpoint digest/step — is written
last as the single commit point. A crash between the two leaves the
previous manifest (and every object it references) fully intact.

Manifest schema v2 (ISSUE 9) extends an entry with an optional
``shards`` list: a checkpoint may be committed as N data objects
(``.shard-iiii-of-nnnn`` keys), each with its own SHA-256/size, written
before the single manifest PUT — the commit stays atomic while
save/restore I/O scales with per-host (1/dp) state for ZeRO-sharded
runs. Single-blob entries serialise exactly as in v1, and v1 manifests
still load (restore treats a blob entry as a 1-shard checkpoint).
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import fsspec

from mingpt_distributed_tpu.telemetry import log_event

MANIFEST_VERSION = 2
#: versions ``from_json`` accepts — v1 manifests (single-blob entries
#: only) predate shard support and must keep restoring
SUPPORTED_MANIFEST_VERSIONS = (1, 2)
MANIFEST_SUFFIX = ".manifest.json"

# -- error classification ---------------------------------------------------

MISSING = "missing"
TRANSIENT = "transient"
PERMANENT = "permanent"

# errno values that mean "the object is not there" rather than "the store
# hiccuped" — ENOENT is the POSIX spelling; some fsspec backends raise a
# bare OSError carrying it instead of FileNotFoundError.
_MISSING_ERRNOS = {errno.ENOENT}
# errors that retrying cannot fix: bad credentials, a directory where a
# file was expected, read-only stores.
_PERMANENT_OSERRORS = (
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def classify_io_error(exc: BaseException) -> str:
    """One shared verdict for every fsspec/OS error the checkpoint layer
    sees: ``missing`` | ``transient`` | ``permanent``.

    Used by both the retry loop (retry only ``transient``) and
    ``load_snapshot`` ("fresh start" only on ``missing``) so the two can
    never disagree about what a given exception means.
    """
    if isinstance(exc, _PERMANENT_OSERRORS):
        return PERMANENT
    if isinstance(exc, FileNotFoundError):
        return MISSING
    if isinstance(exc, OSError):
        if exc.errno in _MISSING_ERRNOS:
            return MISSING
        # covers TimeoutError, ConnectionError, BlockingIOError, and the
        # anonymous OSErrors object-store backends raise on flaky transport
        return TRANSIENT
    return PERMANENT


def is_missing_error(exc: BaseException) -> bool:
    return classify_io_error(exc) == MISSING


class SnapshotIntegrityError(RuntimeError):
    """Every checkpoint referenced by the manifest failed digest or
    deserialisation checks — restoring would load corrupt state, and
    fresh-starting would let the next save overwrite the evidence."""


# -- retry ------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic-seedable jitter.

    ``sleep`` is injectable so tests (and the fault harness) run with zero
    wall-clock delay; ``seed`` pins the jitter sequence.
    """

    attempts: int = 4
    base_delay_s: float = 0.5
    max_delay_s: float = 8.0
    multiplier: float = 2.0
    jitter: float = 0.25          # fraction of the delay randomised away
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def delays(self):
        rng = random.Random(self.seed)
        d = self.base_delay_s
        for _ in range(max(self.attempts - 1, 0)):
            yield d * (1.0 - self.jitter * rng.random())
            d = min(d * self.multiplier, self.max_delay_s)


#: zero-sleep policy for tests and the --selftest-faults smoke
NO_WAIT = RetryPolicy(attempts=4, base_delay_s=0.0, seed=0, sleep=lambda _: None)


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    op: str = "io",
) -> Any:
    """Run ``fn``; retry transient failures per ``policy``.

    ``missing`` and ``permanent`` errors raise immediately (retrying a 404
    or a permission error only delays the inevitable); the last transient
    error raises once attempts are exhausted.
    """
    policy = policy or RetryPolicy()
    delays = policy.delays()
    attempt = 1
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            verdict = classify_io_error(e)
            if verdict != TRANSIENT:
                raise
            try:
                delay = next(delays)
            except StopIteration:
                raise e
            log_event(
                f"[durability] transient {op} error "
                f"(attempt {attempt}/{policy.attempts}): {e!r}; "
                f"retrying in {delay:.2f}s",
                op=op, attempt=attempt,
            )
            policy.sleep(delay)
            attempt += 1


# -- digests ----------------------------------------------------------------


def sha256_hex(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


# -- byte transport (retry-wrapped, atomic where the backend allows) --------


def _is_local(path: str) -> bool:
    return "://" not in path


def write_bytes(
    path: str, blob: bytes, policy: Optional[RetryPolicy] = None
) -> None:
    """Write ``blob`` to ``path`` with retries.

    Local paths write tmp+rename so a killed writer can never leave a torn
    file at the final name. Remote (``://``) paths write the key directly —
    the manifest commit protocol is what makes that safe: an uncommitted
    key is invisible to readers.
    """
    if _is_local(path):
        def put():
            tmp = path + ".tmp"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
    else:
        def put():
            fs, p = fsspec.core.url_to_fs(path)
            with fs.open(p, "wb") as f:
                f.write(blob)
    with_retries(put, policy, op=f"write {path}")


def read_bytes(path: str, policy: Optional[RetryPolicy] = None) -> bytes:
    """Read ``path`` fully, with retries on transient errors. ``missing``
    raises FileNotFoundError-family immediately (callers map it to their
    own semantics — fresh start, or fall back to a previous checkpoint)."""
    def get():
        fs, p = fsspec.core.url_to_fs(path)
        with fs.open(p, "rb") as f:
            return f.read()
    return with_retries(get, policy, op=f"read {path}")


def delete_quiet(path: str) -> None:
    """Best-effort delete (checkpoint rotation): never raises — a
    leftover rotated-out object is garbage, not a correctness problem."""
    try:
        fs, p = fsspec.core.url_to_fs(path)
        fs.rm(p)
    except BaseException:  # noqa: BLE001
        pass


# -- the commit manifest ----------------------------------------------------


@dataclass
class ShardRef:
    """One data object of a sharded checkpoint entry (schema v2)."""

    key: str          # object key, relative to the manifest's directory
    sha256: str
    size: int


@dataclass
class ManifestEntry:
    key: str          # object key, relative to the manifest's directory
    step: int
    epoch: int
    sha256: str       # blob digest; for sharded entries, digest-of-digests
    size: int         # blob size; for sharded entries, total bytes
    #: schema v2: present when the checkpoint was committed as N shard
    #: objects. ``key`` then names shard 0 (so the ``latest`` pointer
    #: stays meaningful) and ``sha256``/``size`` summarise the set.
    shards: Optional[List[ShardRef]] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.shards is None:
            # single-blob entries serialise exactly as schema v1 wrote them
            del d["shards"]
        return d

    @classmethod
    def from_dict(cls, raw: dict) -> "ManifestEntry":
        raw = dict(raw)
        shards = raw.pop("shards", None)
        if shards is not None:
            shards = [ShardRef(**s) for s in shards]
        return cls(shards=shards, **raw)

    def shard_refs(self) -> List[ShardRef]:
        """The entry as a uniform shard list — a v1/single-blob entry is
        its own 1-shard checkpoint."""
        if self.shards is not None:
            return list(self.shards)
        return [ShardRef(key=self.key, sha256=self.sha256, size=self.size)]


@dataclass
class Manifest:
    """``latest`` pointer + ordered checkpoint history, committed as one
    small JSON PUT. Entries are oldest → newest; restore walks newest →
    oldest until a digest-verified checkpoint loads."""

    entries: List[ManifestEntry] = field(default_factory=list)

    @property
    def latest(self) -> Optional[ManifestEntry]:
        return self.entries[-1] if self.entries else None

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": MANIFEST_VERSION,
                "latest": self.latest.key if self.latest else None,
                "checkpoints": [e.to_dict() for e in self.entries],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "Manifest":
        raw = json.loads(text)
        if raw.get("version") not in SUPPORTED_MANIFEST_VERSIONS:
            raise ValueError(
                f"manifest version {raw.get('version')} not in "
                f"{SUPPORTED_MANIFEST_VERSIONS}"
            )
        return cls(
            entries=[
                ManifestEntry.from_dict(e) for e in raw.get("checkpoints", [])
            ]
        )


def manifest_path(snapshot_path: str) -> str:
    return snapshot_path + MANIFEST_SUFFIX


def object_key(snapshot_path: str, step: int) -> str:
    """Step-suffixed data key next to ``snapshot_path`` (never the bare
    path itself — the bare path is reserved for legacy single-blob
    snapshots, which restore still reads)."""
    return f"{snapshot_path}.step-{step:08d}"


def shard_key(snapshot_path: str, step: int, i: int, n: int) -> str:
    """Data key for shard ``i`` of an ``n``-shard checkpoint (schema v2)."""
    return f"{object_key(snapshot_path, step)}.shard-{i:04d}-of-{n:04d}"


def _sibling(snapshot_path: str, key: str) -> str:
    """Resolve a manifest-relative key next to the snapshot path."""
    head = snapshot_path.rsplit("/", 1)[0] if "/" in snapshot_path else "."
    return f"{head}/{key}"


def load_manifest(
    snapshot_path: str, policy: Optional[RetryPolicy] = None
) -> Optional[Manifest]:
    """None = no manifest (legacy layout or fresh run); transient errors
    retry then raise — they must never be mistaken for 'fresh start'."""
    try:
        text = read_bytes(manifest_path(snapshot_path), policy)
    except BaseException as e:  # noqa: BLE001
        if is_missing_error(e):
            return None
        raise
    return Manifest.from_json(text.decode("utf-8"))


def _commit_entry(
    snapshot_path: str,
    entry: ManifestEntry,
    keep: int,
    policy: Optional[RetryPolicy],
) -> ManifestEntry:
    """Manifest update shared by blob and sharded commits: replace any
    same-step entry, append, rotate, ONE manifest PUT (the commit point),
    then best-effort delete of the rotated-out data objects — only after
    the new manifest no longer references them, so no reader can race
    into a dangling pointer."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    manifest = load_manifest(snapshot_path, policy) or Manifest()
    # re-saving the same step replaces that entry (e.g. a retried run that
    # stopped at the same boundary) instead of growing duplicate keys
    manifest.entries = [e for e in manifest.entries if e.step != entry.step]
    manifest.entries.append(entry)
    dropped = manifest.entries[:-keep]
    manifest.entries = manifest.entries[-keep:]
    write_bytes(
        manifest_path(snapshot_path), manifest.to_json().encode(), policy
    )
    for old in dropped:
        for ref in old.shard_refs():
            delete_quiet(_sibling(snapshot_path, ref.key))
    return entry


def commit_blob(
    snapshot_path: str,
    blob: bytes,
    step: int,
    epoch: int,
    keep: int = 3,
    policy: Optional[RetryPolicy] = None,
) -> ManifestEntry:
    """The durable-write protocol: data object first (uncommitted key),
    manifest second (the commit point), rotation last (best effort).
    Returns the committed entry."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    key_path = object_key(snapshot_path, step)
    write_bytes(key_path, blob, policy)
    entry = ManifestEntry(
        key=key_path.rsplit("/", 1)[-1],
        step=int(step),
        epoch=int(epoch),
        sha256=sha256_hex(blob),
        size=len(blob),
    )
    return _commit_entry(snapshot_path, entry, keep, policy)


def commit_shards(
    snapshot_path: str,
    blobs: List[bytes],
    step: int,
    epoch: int,
    keep: int = 3,
    policy: Optional[RetryPolicy] = None,
) -> ManifestEntry:
    """Commit one checkpoint as N data objects (schema v2).

    Every shard is written (each under its own uncommitted key, each
    write individually retried) BEFORE the single manifest PUT commits
    them as a unit — a crash or exhausted retry mid-way leaves the
    previous checkpoint fully intact, exactly like ``commit_blob``. The
    entry-level digest is a digest-of-digests so a whole entry can be
    compared cheaply without re-reading every shard."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if not blobs:
        raise ValueError("commit_shards needs at least one shard")
    if len(blobs) == 1:
        return commit_blob(
            snapshot_path, blobs[0], step, epoch, keep=keep, policy=policy
        )
    n = len(blobs)
    refs = []
    for i, blob in enumerate(blobs):
        key_path = shard_key(snapshot_path, step, i, n)
        write_bytes(key_path, blob, policy)
        refs.append(
            ShardRef(
                key=key_path.rsplit("/", 1)[-1],
                sha256=sha256_hex(blob),
                size=len(blob),
            )
        )
    entry = ManifestEntry(
        key=refs[0].key,
        step=int(step),
        epoch=int(epoch),
        sha256=sha256_hex("".join(r.sha256 for r in refs).encode()),
        size=sum(r.size for r in refs),
        shards=refs,
    )
    return _commit_entry(snapshot_path, entry, keep, policy)


def read_verified_shards(
    snapshot_path: str,
    manifest: Manifest,
    policy: Optional[RetryPolicy] = None,
) -> Tuple[List[bytes], ManifestEntry]:
    """Walk the manifest newest → oldest; return the first checkpoint
    whose every shard reads back with a matching SHA-256. A single-blob
    (v1) entry is treated as a 1-shard checkpoint. Any unreadable or
    digest-mismatched (torn, truncated, bit-flipped) shard fails the
    WHOLE entry — restore falls back to the previous good checkpoint
    instead of crashing or, worse, loading garbage into the optimizer."""
    failures = []
    for entry in reversed(manifest.entries):
        blobs = []
        ok = True
        for ref in entry.shard_refs():
            path = _sibling(snapshot_path, ref.key)
            try:
                blob = read_bytes(path, policy)
            except BaseException as e:  # noqa: BLE001
                if classify_io_error(e) == PERMANENT:
                    raise
                failures.append(f"{ref.key}: unreadable ({e!r})")
                ok = False
                break
            digest = sha256_hex(blob)
            if digest != ref.sha256:
                failures.append(
                    f"{ref.key}: digest mismatch "
                    f"(manifest {ref.sha256[:12]}…, got {digest[:12]}…, "
                    f"{len(blob)}/{ref.size} bytes)"
                )
                ok = False
                break
            blobs.append(blob)
        if not ok:
            continue
        if failures:
            log_event(
                "[durability] fell back to checkpoint "
                f"step {entry.step} after: " + "; ".join(failures),
                step=entry.step,
            )
        return blobs, entry
    raise SnapshotIntegrityError(
        f"no checkpoint in {manifest_path(snapshot_path)} passed "
        f"verification: " + "; ".join(failures)
    )


def read_verified(
    snapshot_path: str,
    manifest: Manifest,
    policy: Optional[RetryPolicy] = None,
) -> Tuple[bytes, ManifestEntry]:
    """Single-payload wrapper over ``read_verified_shards`` (shards of a
    v2 entry are concatenated — only meaningful when the writer's shard
    framing says so; the checkpoint layer uses the shard API directly)."""
    blobs, entry = read_verified_shards(snapshot_path, manifest, policy)
    return b"".join(blobs), entry
