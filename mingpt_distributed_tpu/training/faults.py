"""Deterministic fault injection for fsspec I/O — the chaos harness the
durability layer is tested (and can be manually stressed) against.

``FaultInjectionFileSystem`` registers as the ``faulty://`` protocol and
proxies every operation to a target filesystem (local by default), while
a list of :class:`FaultSpec` rules decides which operations to sabotage:

* fail the **Nth** write/read, or **every** Kth one, with a transient
  ``OSError`` — exercises the retry + backoff path;
* **truncate** a write (half the bytes land, the call "succeeds") —
  exercises digest verification and previous-good fallback;
* **delay** an operation — exercises timeout behaviour under load;
* report a blob as **missing** — exercises missing-vs-transient
  classification.

Counters advance deterministically per matching operation (a whole-object
open-for-write or open-for-read is one operation — the granularity of an
object-store PUT/GET), so a given spec produces the same fault schedule
every run. Specs come from the constructor, :meth:`set_faults`, or the
``MINGPT_FAULTS`` environment variable, so the same machinery is a unit
-test fixture, a ``--selftest-faults`` smoke, and a manual chaos knob for
a real training run::

    MINGPT_FAULTS="write:every=3" python train.py \\
        trainer_config.snapshot_path=faulty:///ckpt/run1/snap.msgpack

Spec grammar (semicolon-separated): ``op[:field=value]...`` with fields
``nth`` (1-based one-shot), ``every`` (periodic), ``mode``
(``error`` | ``truncate`` | ``delay`` | ``missing``), ``match``
(substring filter on the path), ``delay`` (seconds, for mode=delay).

Serving fault points (ISSUE 6): the same spec grammar and deterministic
counters drive :class:`ServingFaultInjector`, whose ops sabotage the
fleet's scheduling loop instead of the filesystem — ``crash`` (replica
dies mid-decode, in-flight requests must retry on survivors), ``poison``
(one scheduling round raises after the compiled step, before emission),
``slow`` (virtual clock skew — NEVER a wall-clock sleep, so chaos tests
stay fast and deterministic), ``admit`` (submission raises). ``match``
filters on the replica name (``match=replica0``); the env knob is
``MINGPT_SERVING_FAULTS``::

    MINGPT_SERVING_FAULTS="crash:nth=6:match=replica0;slow:every=1:delay=0.25:match=replica1" \\
        python serve.py --replicas 3 ...

Process fault points (ISSUE 16): :class:`ProcessFaultInjector` drives the
process-isolated fleet (``serving/procfleet``) with ops that sabotage the
RPC boundary instead of the scheduling loop — ``kill`` (the replica
process dies as if SIGKILLed; over a real socket the supervisor actually
sends SIGKILL), ``hang`` (one RPC times out; the replica survives, the
round is lost), ``slow_socket`` (the RPC is slow: virtual clock skew on
the deterministic loopback transport, or an injectable ``sleep`` per the
``RetryPolicy.sleep`` idiom when a real socket is in play), and
``stuck_step`` (the worker *enters* the step RPC and never returns —
distinct from a socket-level ``hang``, which loses one round and moves
on: a stuck worker stays wedged, every later RPC times out too, and
only the supervisor's SIGTERM→SIGKILL escalation ladder recovers it).
``match`` filters on the replica name; the env knob is
``MINGPT_PROCESS_FAULTS``.
"""

from __future__ import annotations

import errno
import io
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import fsspec
from fsspec import AbstractFileSystem

ENV_VAR = "MINGPT_FAULTS"
ENV_TARGET = "MINGPT_FAULT_TARGET"
SERVING_ENV_VAR = "MINGPT_SERVING_FAULTS"
PROCESS_ENV_VAR = "MINGPT_PROCESS_FAULTS"
NET_ENV_VAR = "MINGPT_NET_FAULTS"

#: Filesystem fault points (the original grammar) vs serving fault points
#: (fleet chaos harness) vs process fault points (procfleet RPC boundary)
#: vs network fault points (hostplane mesh, ISSUE 19). One FaultSpec
#: grammar covers all four; which set an injector accepts is validated
#: at construction.
IO_OPS = ("write", "read")
SERVING_OPS = ("crash", "poison", "slow", "admit")
PROCESS_OPS = ("kill", "hang", "slow_socket", "stuck_step")
NET_OPS = ("partition", "drop_frame", "slow_link", "host_kill")


@dataclass
class FaultSpec:
    """One sabotage rule. ``count`` is the number of operations of ``op``
    seen so far that matched ``match`` — the deterministic clock faults
    fire against."""

    op: str                       # "write" | "read"
    nth: int = 0                  # fire on exactly this matching op (1-based)
    every: int = 0                # fire on every k-th matching op
    mode: str = "error"           # "error" | "truncate" | "delay" | "missing"
    match: str = ""               # substring filter on the path
    delay_s: float = 0.0
    count: int = field(default=0, compare=False)

    def __post_init__(self):
        known = IO_OPS + SERVING_OPS + PROCESS_OPS + NET_OPS
        if self.op not in known:
            raise ValueError(
                f"fault op must be one of {known}, got {self.op!r}")
        if self.op in ("slow", "slow_socket", "slow_link") \
                and self.mode == "error":
            # slowness only makes sense as a delay; default the mode so
            # specs read naturally ("slow:every=1:delay=0.25")
            self.mode = "delay"
        if self.mode not in ("error", "truncate", "delay", "missing"):
            raise ValueError(f"unknown fault mode {self.mode!r}")
        if not self.nth and not self.every:
            raise ValueError("fault spec needs nth=N or every=K")

    def fires(self, op: str, path: str) -> bool:
        """Advance the clock if (op, path) matches; True when the fault
        should trigger on this operation."""
        if op != self.op or (self.match and self.match not in path):
            return False
        self.count += 1
        if self.nth and self.count == self.nth:
            return True
        if self.every and self.count % self.every == 0:
            return True
        return False


def parse_faults(text: str) -> List[FaultSpec]:
    """``"write:every=3;read:nth=2:mode=truncate"`` -> [FaultSpec, ...]."""
    specs: List[FaultSpec] = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        kwargs: dict = {"op": fields[0].strip()}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(f"malformed fault field {f!r} in {part!r}")
            k, v = f.split("=", 1)
            k = k.strip()
            if k in ("nth", "every"):
                kwargs[k] = int(v)
            elif k == "delay":
                kwargs["delay_s"] = float(v)
            elif k in ("mode", "match"):
                kwargs[k] = v.strip()
            else:
                raise ValueError(f"unknown fault field {k!r} in {part!r}")
        specs.append(FaultSpec(**kwargs))
    return specs


def _injected_error(op: str, path: str) -> OSError:
    # EIO without a FileNotFoundError subclass -> classified TRANSIENT by
    # durability.classify_io_error, which is the point: retries must engage
    return OSError(errno.EIO, f"injected transient {op} failure", path)


class _FaultyWriteFile(io.BytesIO):
    """Buffers the whole object; the fault verdict lands at close() —
    whole-object semantics matching an object-store PUT. ``truncate``
    writes half the bytes and reports success (silent corruption, the
    digest check's job to catch); ``error`` writes nothing and raises."""

    def __init__(self, target_fs, path: str, mode: Optional[str],
                 delay_s: float,
                 sleep: Callable[[float], None] = time.sleep):
        super().__init__()
        self._target_fs = target_fs
        self._path = path
        self._fault = mode
        self._delay_s = delay_s
        self._sleep = sleep
        self._done = False

    def close(self):
        if self._done or self.closed:
            return
        self._done = True
        blob = self.getvalue()
        super().close()
        if self._fault == "error":
            raise _injected_error("write", self._path)
        if self._fault == "delay":
            self._sleep(self._delay_s)
        if self._fault == "truncate":
            blob = blob[: len(blob) // 2]
        with self._target_fs.open(self._path, "wb") as f:
            f.write(blob)


class FaultInjectionFileSystem(AbstractFileSystem):
    """fsspec filesystem that proxies ``faulty://<path>`` to a target
    filesystem (``target_protocol``, default local) through the fault
    rules. Instances are cached by fsspec, so counters persist across
    ``fsspec.open`` calls — the schedule is process-global and
    deterministic."""

    protocol = "faulty"
    cachable = True

    def __init__(
        self,
        faults: Optional[str] = None,
        target_protocol: Optional[str] = None,
        target_options: Optional[dict] = None,
        sleep: Callable[[float], None] = time.sleep,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        # injectable like durability.RetryPolicy.sleep — delay faults
        # become instantaneous (and assertable) under a fake sleep
        self.sleep = sleep
        self.target = fsspec.filesystem(
            target_protocol or os.environ.get(ENV_TARGET, "file"),
            **(target_options or {}),
        )
        spec_text = faults if faults is not None else os.environ.get(ENV_VAR, "")
        self.specs: List[FaultSpec] = parse_faults(spec_text)

    # -- harness controls ----------------------------------------------
    def set_faults(self, text: str) -> None:
        """Replace the rule set and reset all counters."""
        self.specs = parse_faults(text)

    def clear_faults(self) -> None:
        self.specs = []

    def reset_counters(self) -> None:
        for s in self.specs:
            s.count = 0

    def _fault_for(self, op: str, path: str) -> Optional[FaultSpec]:
        for s in self.specs:
            if s.fires(op, path):
                return s
        return None

    # -- fsspec surface ------------------------------------------------
    @classmethod
    def _strip_protocol(cls, path):
        path = fsspec.utils.stringify_path(path)
        if path.startswith(cls.protocol + "://"):
            path = path[len(cls.protocol) + 3:]
        return path or "/"

    def _open(self, path, mode="rb", **kwargs):
        if "w" in mode or "a" in mode or "x" in mode:
            spec = self._fault_for("write", path)
            return _FaultyWriteFile(
                self.target, path,
                spec.mode if spec else None,
                spec.delay_s if spec else 0.0,
                sleep=self.sleep,
            )
        spec = self._fault_for("read", path)
        if spec is not None:
            if spec.mode == "missing":
                raise FileNotFoundError(errno.ENOENT,
                                        "injected missing object", path)
            if spec.mode == "error":
                raise _injected_error("read", path)
            if spec.mode == "delay":
                self.sleep(spec.delay_s)
            if spec.mode == "truncate":
                with self.target.open(path, "rb") as f:
                    blob = f.read()
                return io.BytesIO(blob[: len(blob) // 2])
        return self.target.open(path, mode, **kwargs)

    # plain delegation — faults apply only to data-plane read/write
    def info(self, path, **kwargs):
        return self.target.info(path, **kwargs)

    def ls(self, path, detail=True, **kwargs):
        return self.target.ls(path, detail=detail, **kwargs)

    def exists(self, path, **kwargs):
        return self.target.exists(path, **kwargs)

    def rm_file(self, path):
        return self.target.rm_file(path)

    def rm(self, path, recursive=False, maxdepth=None):
        return self.target.rm(path, recursive=recursive, maxdepth=maxdepth)

    def makedirs(self, path, exist_ok=False):
        return self.target.makedirs(path, exist_ok=exist_ok)

    def mkdir(self, path, create_parents=True, **kwargs):
        return self.target.mkdir(path, create_parents=create_parents, **kwargs)


# ---------------------------------------------------------------------
# Serving chaos harness (ISSUE 6)
# ---------------------------------------------------------------------

class InjectedServingFault(RuntimeError):
    """Base of every fault the serving injector raises — the fleet layer
    treats these exactly like organic replica failures (that's the
    point), but tests can assert on the type."""


class ReplicaCrashed(InjectedServingFault):
    """The replica process 'died' mid-round: its engine/server object
    must never be reused (host-side state may be mid-update); the
    supervisor replaces it with a fresh server."""


class InjectedAdmissionError(InjectedServingFault):
    """submit() failed on this replica — routing should retry the
    request elsewhere, not fail it."""


class ServingFaultInjector:
    """Deterministic fault schedule over the fleet's serving fault points,
    sharing :class:`FaultSpec`'s grammar and counters with the I/O
    injector. ``match`` filters on the replica name.

    Fault points (where the fleet calls in):

    * ``step_delay(replica)`` — before a replica's scheduling round.
      Returns the virtual seconds of injected slowness (``slow`` specs;
      the replica's *clock* is skewed — no wall sleep ever happens) and
      raises :class:`ReplicaCrashed` for a due ``crash`` spec.
    * ``round_hook(replica)`` — an ``InferenceServer.fault_hook``: a due
      ``poison`` spec raises :class:`InjectedServingFault` mid-round,
      after the compiled decode step but before any token is emitted.
    * ``check_admit(replica)`` — inside replica submit; a due ``admit``
      spec raises :class:`InjectedAdmissionError`.

    Counters advance once per fault-point visit per matching spec, so a
    given (spec, request schedule) pair produces the same chaos every
    run — chaos tests are seeds, not dice.
    """

    def __init__(self, faults: Optional[str] = None):
        text = faults if faults is not None else os.environ.get(
            SERVING_ENV_VAR, "")
        self.specs = parse_faults(text)
        for s in self.specs:
            if s.op not in SERVING_OPS:
                raise ValueError(
                    f"serving fault op must be one of {SERVING_OPS}, "
                    f"got {s.op!r} (I/O ops belong in {ENV_VAR})")
        self.fired: List[str] = []  # "(op, replica)" audit trail

    def _fire(self, op: str, replica: str) -> Optional[FaultSpec]:
        for s in self.specs:
            if s.fires(op, replica):
                self.fired.append(f"{op}:{replica}")
                return s
        return None

    def reset_counters(self) -> None:
        for s in self.specs:
            s.count = 0
        self.fired = []

    # -- fault points ---------------------------------------------------
    def step_delay(self, replica: str) -> float:
        """Crash/slow verdict for one scheduling round of ``replica``.
        Raises ReplicaCrashed or returns injected VIRTUAL delay seconds
        (0.0 when healthy). The caller adds the delay to the replica's
        clock skew; nothing here ever sleeps."""
        if self._fire("crash", replica) is not None:
            raise ReplicaCrashed(
                f"injected crash: replica {replica} died mid-round")
        spec = self._fire("slow", replica)
        return spec.delay_s if spec is not None else 0.0

    def round_hook(self, replica: str):
        """An ``InferenceServer.fault_hook`` poisoning this replica's
        scheduling round at the named fault point."""
        def hook(where: str) -> None:
            if self._fire("poison", replica) is not None:
                raise InjectedServingFault(
                    f"injected poison: replica {replica} raised at "
                    f"{where}")
        return hook

    def check_admit(self, replica: str) -> None:
        if self._fire("admit", replica) is not None:
            raise InjectedAdmissionError(
                f"injected admission failure on replica {replica}")


class ProcessKilled(ReplicaCrashed):
    """The replica *process* died (SIGKILL-grade: no goodbye over the
    socket). Subclasses :class:`ReplicaCrashed` so the router's crash
    path — trip breaker, mark crashed, retry victims — applies
    unchanged; the process supervisor additionally reaps the corpse and
    collects its flight-recorder spill."""


class InjectedHang(InjectedServingFault):
    """One RPC to the replica timed out (socket-level hang). The process
    is still alive; the round is lost, the breaker records a failure —
    the same contract as a poisoned in-process round."""


class WorkerStuck(InjectedHang):
    """The worker entered the step RPC and never returned. Unlike a
    plain ``hang`` this is *sticky*: the injector remembers the wedge,
    so every subsequent RPC to the same replica times out too — waitpid
    sees a live process, the socket sees only timeouts, and the only way
    out is the supervisor's liveness deadline escalating
    SIGTERM → SIGKILL."""


class ProcessFaultInjector:
    """Deterministic fault schedule over the procfleet RPC boundary,
    sharing :class:`FaultSpec`'s grammar and counters with the other
    injectors. ``match`` filters on the replica name. One fault point:

    * ``rpc_verdict(replica)`` — before each step RPC. Raises
      :class:`ProcessKilled` for a due ``kill`` (over a real socket the
      supervisor turns this into an actual SIGKILL of the subprocess),
      raises :class:`InjectedHang` for a due ``hang``, raises
      :class:`WorkerStuck` for a due ``stuck_step`` — and, because a
      stuck worker never comes back on its own, keeps raising
      ``WorkerStuck`` for that replica on every later call until
      :meth:`reset` (the supervisor resets on respawn) — and returns
      the injected delay seconds for a due ``slow_socket`` (0.0
      otherwise).

    ``sleep`` is injectable per the ``RetryPolicy.sleep`` idiom: the
    deterministic loopback transport leaves it ``None`` and lands the
    delay as clock skew (nobody sleeps); a real-socket fleet may pass
    ``time.sleep`` so slowness is physically observable end-to-end."""

    def __init__(self, faults: Optional[str] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        text = faults if faults is not None else os.environ.get(
            PROCESS_ENV_VAR, "")
        self.specs = parse_faults(text)
        for s in self.specs:
            if s.op not in PROCESS_OPS:
                raise ValueError(
                    f"process fault op must be one of {PROCESS_OPS}, "
                    f"got {s.op!r} (serving ops belong in "
                    f"{SERVING_ENV_VAR})")
        self.sleep = sleep
        self.fired: List[str] = []  # "(op, replica)" audit trail
        self._stuck: set = set()    # replicas wedged by stuck_step

    def _fire(self, op: str, replica: str) -> Optional[FaultSpec]:
        for s in self.specs:
            if s.fires(op, replica):
                self.fired.append(f"{op}:{replica}")
                return s
        return None

    def reset_counters(self) -> None:
        for s in self.specs:
            s.count = 0
        self.fired = []
        self._stuck = set()

    def is_stuck(self, replica: str) -> bool:
        """True once a ``stuck_step`` fired for ``replica`` and it has
        not been :meth:`reset` — the wedge is permanent until the
        supervisor replaces the process."""
        return replica in self._stuck

    def reset(self, replica: str) -> None:
        """Clear the wedge for ``replica`` — called by the supervisor on
        respawn (the replacement process is not stuck)."""
        self._stuck.discard(replica)

    def rpc_verdict(self, replica: str) -> float:
        """Kill/hang/slow/stuck verdict for one RPC round against
        ``replica``. Raises ProcessKilled, InjectedHang or WorkerStuck,
        or returns injected delay seconds. When ``sleep`` was injected
        the delay is slept here and 0.0 is returned (real-socket mode);
        otherwise the caller adds it to the replica's clock skew
        (deterministic loopback mode)."""
        if replica in self._stuck:
            raise WorkerStuck(
                f"replica {replica} is wedged in step; RPC timed out")
        if self._fire("kill", replica) is not None:
            raise ProcessKilled(
                f"injected kill: replica process {replica} died")
        if self._fire("hang", replica) is not None:
            raise InjectedHang(
                f"injected hang: RPC to replica {replica} timed out")
        if self._fire("stuck_step", replica) is not None:
            self._stuck.add(replica)
            raise WorkerStuck(
                f"injected stuck_step: replica {replica} entered step "
                f"and never returned")
        spec = self._fire("slow_socket", replica)
        if spec is None:
            return 0.0
        if self.sleep is not None:
            self.sleep(spec.delay_s)
            return 0.0
        return spec.delay_s


class LinkPartitioned(InjectedServingFault):
    """The host-to-host link is partitioned: the frame never arrives and
    the caller sees a transport failure, exactly like a cable pull. The
    hostplane's heartbeat ladder — not this exception — decides when the
    peer is *suspect* vs *dead*."""


class NetworkFaultInjector:
    """Deterministic fault schedule over the hostplane mesh (ISSUE 19),
    sharing :class:`FaultSpec`'s grammar and counters with the other
    injectors. ``match`` filters on the **link key** ``"src->dst"`` (so
    ``match=host0`` partitions every link touching host0, and
    ``match=host0->host1`` exactly one direction). Env knob:
    ``MINGPT_NET_FAULTS``. Fault points:

    * ``link_verdict(src, dst)`` — before any frame crosses the link.
      A due ``partition`` opens the partition (for ``delay`` virtual
      seconds on the injected clock, or until :meth:`heal` when no delay
      is given) and every call while it is open raises
      :class:`LinkPartitioned`; a due ``slow_link`` returns the extra
      seconds the frame takes (the PacedChannel charges them against its
      bandwidth clock — nothing here ever sleeps).
    * ``frame_verdict(src, dst)`` — per transfer-channel chunk; a due
      ``drop_frame`` returns True and the chunk is lost (the resumable
      transfer retries from the last acked chunk).
    * ``host_verdict(host)`` — a due ``host_kill`` returns True and the
      whole host dies (every replica SIGKILLed, agent stops answering
      heartbeats).
    """

    def __init__(self, faults: Optional[str] = None, clock=None):
        text = faults if faults is not None else os.environ.get(
            NET_ENV_VAR, "")
        self.specs = parse_faults(text)
        for s in self.specs:
            if s.op not in NET_OPS:
                raise ValueError(
                    f"network fault op must be one of {NET_OPS}, "
                    f"got {s.op!r} (process ops belong in "
                    f"{PROCESS_ENV_VAR})")
        self.clock = clock
        self.fired: List[str] = []            # "op:link" audit trail
        #: link key -> virtual deadline (None = open until heal())
        self._partitions: dict = {}

    def _fire(self, op: str, key: str) -> Optional[FaultSpec]:
        for s in self.specs:
            if s.fires(op, key):
                self.fired.append(f"{op}:{key}")
                return s
        return None

    def reset_counters(self) -> None:
        for s in self.specs:
            s.count = 0
        self.fired = []
        self._partitions = {}

    def heal(self) -> None:
        """Close every open partition (the cable is plugged back in).
        Spec counters keep advancing — a periodic partition can re-open
        later; only :meth:`reset_counters` rewinds the schedule."""
        self._partitions = {}

    def _partition_open(self, key: str) -> bool:
        if key not in self._partitions:
            return False
        until = self._partitions[key]
        if until is None:
            return True
        now = self.clock.now() if self.clock is not None else 0.0
        if now >= until:
            del self._partitions[key]
            return False
        return True

    # -- fault points ---------------------------------------------------
    def link_verdict(self, src: str, dst: str) -> float:
        """Partition/slow verdict for one frame over ``src->dst``.
        Raises :class:`LinkPartitioned` or returns extra seconds of
        injected link slowness (0.0 when healthy)."""
        key = f"{src}->{dst}"
        spec = self._fire("partition", key)
        if spec is not None:
            until = None
            if spec.delay_s > 0 and self.clock is not None:
                until = self.clock.now() + spec.delay_s
            self._partitions[key] = until
        if self._partition_open(key):
            raise LinkPartitioned(f"injected partition: link {key} is down")
        spec = self._fire("slow_link", key)
        return spec.delay_s if spec is not None else 0.0

    def frame_verdict(self, src: str, dst: str) -> bool:
        """True when this transfer-channel chunk should be dropped."""
        return self._fire("drop_frame", f"{src}->{dst}") is not None

    def host_verdict(self, host: str) -> bool:
        """True when ``host`` should die wholesale on this check."""
        return self._fire("host_kill", host) is not None


def register() -> None:
    """Idempotently register ``faulty://`` with fsspec. Imported lazily by
    train.py/tests; importing this module is enough."""
    fsspec.register_implementation(
        "faulty", FaultInjectionFileSystem, clobber=True
    )


register()
