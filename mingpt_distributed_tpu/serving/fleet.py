"""Resilient multi-replica serving fabric (ISSUE 6 tentpole).

One ``InferenceServer`` is a single failure domain: a crash loses every
accepted request, an overload stalls all of them, and there is no second
process to absorb either. This module adds the fleet layer the ROADMAP's
"millions of users" item calls for, as in-process CPU replicas first —
the same supervision/routing API later fronts per-mesh replicas:

* :class:`ReplicaSupervisor` — owns N :class:`Replica` wrappers, each a
  full ``InferenceServer`` (own engine, KV pool, prefix store, private
  metrics). A crashed replica's server object is **never reused** (its
  host-side slot state may be mid-update); the supervisor respawns a
  fresh server after a backoff, within a bounded restart budget.
* :class:`Replica.health` — readiness derived from the telemetry the
  replica already exports: crashed state, queue depth over the
  watermark, ITL p99 over the SLO (ladder-resolution quantile from the
  shared histogram), post-warmup recompiles counted by the watchdog.
* :class:`Router` — fans a request stream across replicas:
  prefix-affinity placement (CRC32 of the prompt head, so shared-prefix
  tenants land where `PrefixKVStore` already holds their rows), healthy
  replicas preferred over unhealthy-but-alive ones, least-loaded within
  a tier; per-replica :class:`CircuitBreaker` with half-open probing;
  bounded retry-with-backoff of crashed/failed requests onto survivors;
  deadline-aware load shedding; graceful drain.

**Retry idempotency invariant.** A retried request is re-submitted from
the ORIGINAL prompt — never from partial KV state — and the scheduler's
determinism guarantee (greedy output depends only on params + prompt +
sampling params + seed, never on co-tenants) means the new attempt
regenerates the same token at every index. The router's emitter dedups
by token index: positions already streamed to the caller are suppressed
(counted in ``mingpt_fleet_duplicate_tokens_suppressed_total``), so the
caller-visible stream is append-only and token-identical to solo
``generate()`` no matter how many times the request bounced. The
scheduler cooperates by placing its chaos fault point AFTER the compiled
decode step but BEFORE emission: a replica failing mid-round loses
computed tokens, it never double-streams them.

**Time.** The whole fabric runs on an injected clock. Chaos tests and
``serve.py --selftest-chaos`` use :class:`VirtualClock` (one tick per
router round — deterministic, zero wall-clock sleeps; an injected "slow"
fault skews one replica's :class:`SkewedClock`, which inflates its
observed ITL and trips the health gate without anyone sleeping). Live
serving uses :class:`WallClock`. Backoffs, breaker reset windows and
deadlines are all expressed in the active clock's seconds.

Exit code 75 (``REQUEUE_EXIT_CODE``, EX_TEMPFAIL) mirrors trainer.py's
preemption path: serve.py exits with it after a SIGTERM-triggered drain
so schedulers requeue rather than fail the job.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from mingpt_distributed_tpu.serving.admission import AdmissionPolicy
from mingpt_distributed_tpu.serving.requests import (
    QueueFullError,
    Request,
    RequestHandle,
    ShedError,
)
from mingpt_distributed_tpu.serving.scheduler import InferenceServer
from mingpt_distributed_tpu.telemetry import (
    MetricsRegistry,
    render_fleet_prometheus,
    render_prometheus,
)
from mingpt_distributed_tpu.telemetry.flightrec import FlightRecorder
from mingpt_distributed_tpu.telemetry.tracing import (
    TraceContext,
    TraceRecorder,
    trace_baggage,
)
from mingpt_distributed_tpu.training.faults import (
    InjectedAdmissionError,
    ReplicaCrashed,
    ServingFaultInjector,
)

#: Same convention as trainer.py (EX_TEMPFAIL): "requeue me, don't fail
#: me" — defined locally so the serving path never imports the trainer.
REQUEUE_EXIT_CODE = 75

__all__ = [
    "CircuitBreaker",
    "FleetHandle",
    "REQUEUE_EXIT_CODE",
    "Replica",
    "ReplicaHealth",
    "ReplicaSupervisor",
    "Router",
    "SkewedClock",
    "VirtualClock",
    "WallClock",
    "default_server_factory",
]


# ---------------------------------------------------------------------
# Clocks
# ---------------------------------------------------------------------

class VirtualClock:
    """Deterministic fleet time: advances only when told to. The router
    calls ``tick()`` once per scheduling round, so backoffs / breaker
    reset windows / deadlines are measured in rounds × ``tick_s`` and a
    chaos run is bit-reproducible with zero wall sleeps."""

    def __init__(self, tick_s: float = 0.001, start: float = 0.0):
        self.tick_s = tick_s
        self.t = start

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt

    def tick(self) -> None:
        self.t += self.tick_s


class WallClock:
    """Real time, same surface as VirtualClock (tick/advance are no-ops
    — wall time advances itself)."""

    def now(self) -> float:
        return time.perf_counter()

    def advance(self, dt: float) -> None:
        pass

    def tick(self) -> None:
        pass


class SkewedClock:
    """A replica's view of fleet time: base clock + accumulated skew.
    An injected "slow" fault adds its virtual delay to ``skew_s``, so the
    replica *observes* inflated latencies (ITL p99 crosses the SLO, the
    health gate fires) while the test harness never sleeps. Monotonic as
    long as skew only grows."""

    def __init__(self, base: Callable[[], float]):
        self.base = base
        self.skew_s = 0.0

    def __call__(self) -> float:
        return self.base() + self.skew_s


# ---------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------

class CircuitBreaker:
    """Per-replica admission gate. States (the gauge encoding in
    ``mingpt_fleet_breaker_state{replica}``):

    * ``CLOSED`` (0) — admitting; ``failure_threshold`` consecutive
      failures open it.
    * ``OPEN`` (2) — refusing; after ``reset_after_s`` the next
      ``allow()`` moves to half-open.
    * ``HALF_OPEN`` (1) — exactly one probe request may enter
      (``start_probe()``); its success closes the breaker, any failure
      while half-open re-opens immediately.
    """

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(
        self,
        clock: Callable[[], float],
        failure_threshold: int = 3,
        reset_after_s: float = 1.0,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self.state = self.CLOSED
        self.failures = 0
        self.opened_at: Optional[float] = None
        self._probe_out = False

    def allow(self) -> bool:
        if self.state == self.OPEN:
            if self.clock() - (self.opened_at or 0.0) >= self.reset_after_s:
                self.state = self.HALF_OPEN
                self._probe_out = False
            else:
                return False
        if self.state == self.HALF_OPEN:
            return not self._probe_out
        return True

    def start_probe(self) -> None:
        """The caller routed a request through a half-open breaker — no
        further requests until its verdict lands."""
        if self.state == self.HALF_OPEN:
            self._probe_out = True

    def record_success(self) -> None:
        self.state = self.CLOSED
        self.failures = 0
        self._probe_out = False

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == self.HALF_OPEN or self.failures >= self.failure_threshold:
            self._open()

    def trip(self) -> None:
        """Immediate open — a crash is not a 'failure budget' event."""
        self._open()

    def reset_to_probe(self) -> None:
        """A restarted replica goes straight to half-open: one probe
        verifies the fresh server before full traffic returns."""
        self.state = self.HALF_OPEN
        self.failures = 0
        self._probe_out = False

    def _open(self) -> None:
        self.state = self.OPEN
        self.opened_at = self.clock()
        self._probe_out = False


# ---------------------------------------------------------------------
# Replica + supervisor
# ---------------------------------------------------------------------

@dataclass
class ReplicaHealth:
    ready: bool
    reasons: List[str] = field(default_factory=list)


class Replica:
    """One supervised ``InferenceServer`` with its own skewed clock and
    the injector's fault points wired into its lifecycle."""

    #: control-plane scale-down flag: a draining replica keeps stepping
    #: (its in-flight streams finish in place — never re-routed) but the
    #: router stops placing new work on it; once idle the controller
    #: retires it through ``ReplicaSupervisor.retire_replica``
    draining = False

    def __init__(
        self,
        name: str,
        index: int,
        server_factory: Callable[..., InferenceServer],
        fleet_clock,
        injector: Optional[ServingFaultInjector] = None,
        queue_high_watermark: int = 8,
        itl_slo_s: Optional[float] = None,
    ):
        self.name = name
        self.index = index
        self._factory = server_factory
        self.clock = SkewedClock(fleet_clock.now)
        self.injector = injector
        self.queue_high_watermark = queue_high_watermark
        self.itl_slo_s = itl_slo_s
        self.state = "ready"          # "ready" | "crashed"
        self.crashes = 0
        self.crashed_at: Optional[float] = None  # fleet-clock crash time
        self.last_spawn_path = "cold"            # "cold" | "standby"
        self.server: InferenceServer = self._spawn()

    def _spawn(self) -> InferenceServer:
        hook = (self.injector.round_hook(self.name)
                if self.injector is not None else None)
        return self._factory(name=self.name, clock=self.clock,
                             fault_hook=hook)

    def respawn(self) -> None:
        """Replace the crashed server with a fresh one. The old object —
        engine, KV pool, slot table — is dropped, never reused: a crash
        mid-round may have left host-side slot state half-updated."""
        self.server = self._spawn()
        self.state = "ready"
        self.draining = False

    def submit(self, request: Request) -> RequestHandle:
        if self.injector is not None:
            self.injector.check_admit(self.name)
        return self.server.submit(request)

    def step(self) -> bool:
        if self.injector is not None:
            # may raise ReplicaCrashed; a "slow" fault lands as clock
            # skew — this replica observes the delay, nobody sleeps it
            self.clock.skew_s += self.injector.step_delay(self.name)
        return self.server.step()

    @property
    def load(self) -> int:
        return len(self.server.queue) + self.server.slots.occupied

    def health(self) -> ReplicaHealth:
        """Readiness from signals the replica already exports — the same
        numbers a /healthz endpoint would gate on."""
        reasons: List[str] = []
        if self.state == "drained":
            return ReplicaHealth(False, ["drained"])
        if self.state != "ready":
            reasons.append("crashed")
            return ReplicaHealth(False, reasons)
        if self.draining:
            reasons.append("draining")
        if len(self.server.queue) > self.queue_high_watermark:
            reasons.append("queue_depth")
        if self.itl_slo_s is not None:
            p99 = self.server.metrics.itl_p99_s
            if p99 is not None and p99 > self.itl_slo_s:
                reasons.append("itl_p99")
        if self.server.watchdog.recompiles > 0:
            reasons.append("recompiles")
        return ReplicaHealth(not reasons, reasons)


class ReplicaSupervisor:
    """Owns the replica set and the crash→backoff→respawn lifecycle.
    Restart policy: each crash schedules a respawn ``restart_backoff_s ×
    2^(restarts so far)`` in the future, up to ``max_restarts`` per
    replica; past the budget the replica stays down (flapping hardware
    should not be hammered forever)."""

    #: Replica wrapper class — subclasses swap in a different isolation
    #: boundary (procfleet's ProcReplica) without copying the lifecycle.
    replica_cls = Replica

    def __init__(
        self,
        server_factory: Callable[..., InferenceServer],
        n_replicas: int = 2,
        clock=None,
        injector: Optional[ServingFaultInjector] = None,
        registry: Optional[MetricsRegistry] = None,
        max_restarts: int = 1,
        restart_backoff_s: float = 0.05,
        queue_high_watermark: int = 8,
        itl_slo_s: Optional[float] = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.clock = clock if clock is not None else VirtualClock()
        self.injector = injector
        self.registry = registry if registry is not None else MetricsRegistry()
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        # kept for control-plane scale-up: spawn_replica builds late
        # replicas with the same factory/health gates as the first N
        self._server_factory = server_factory
        self.queue_high_watermark = queue_high_watermark
        self.itl_slo_s = itl_slo_s
        self._next_index = n_replicas
        self.replicas = [
            self.replica_cls(
                f"replica{i}", i, server_factory, self.clock, injector,
                queue_high_watermark=queue_high_watermark,
                itl_slo_s=itl_slo_s)
            for i in range(n_replicas)
        ]
        r = self.registry
        self._up = r.gauge(
            "mingpt_fleet_replica_up",
            help="1 while the replica's server is alive (0 = crashed, "
                 "awaiting restart or out of restart budget)",
            labels=("replica",))
        self._healthy = r.gauge(
            "mingpt_fleet_replica_healthy",
            help="1 while up AND passing every health gate (queue depth, "
                 "ITL p99 SLO, recompile watchdog)",
            labels=("replica",))
        self._crashes = r.counter(
            "mingpt_fleet_crashes_total",
            help="replica crashes observed by the supervisor",
            labels=("replica",))
        self._restarts = r.counter(
            "mingpt_fleet_restarts_total",
            help="fresh servers spawned to replace crashed ones",
            labels=("replica",))
        self._recovery = r.histogram(
            "mingpt_fleet_recovery_seconds",
            help="crash -> replacement-serving time per respawn, by "
                 "path: cold = spawn + restore + compile, standby = "
                 "adopt a pre-warmed spare (ISSUE 17)",
            labels=("path",))
        for rep in self.replicas:
            self._up.labels(replica=rep.name).set(1)
            self._healthy.labels(replica=rep.name).set(1)
            self._crashes.labels(replica=rep.name).inc(0)
            self._restarts.labels(replica=rep.name).inc(0)
        self._restart_due: Dict[str, float] = {}
        self._restarts_used: Dict[str, int] = {}
        #: respawn post-mortems in crash order: {replica, path,
        #: recovery_s, adopted} — the chaos gates compare cold vs
        #: standby recovery on these recorded numbers
        self.recovery_log: List[Dict] = []
        self.last_recovery: Dict[str, Dict] = {}

    def replica_by_name(self, name: str) -> Optional[Replica]:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        return None

    def mark_crashed(self, replica: Replica) -> None:
        replica.state = "crashed"
        replica.crashes += 1
        replica.crashed_at = self.clock.now()
        self._crashes.labels(replica=replica.name).inc()
        self._up.labels(replica=replica.name).set(0)
        used = self._restarts_used.get(replica.name, 0)
        if used < self.max_restarts:
            self._restart_due[replica.name] = (
                self.clock.now() + self.restart_backoff_s * (2 ** used))

    def restarts_scheduled(self) -> bool:
        return bool(self._restart_due)

    def poll_restarts(self) -> List[Replica]:
        """Respawn every replica whose backoff elapsed; returns them so
        the router can rewire streaming + move breakers to half-open."""
        now = self.clock.now()
        restarted: List[Replica] = []
        for name, due in sorted(self._restart_due.items()):
            if now < due:
                continue
            del self._restart_due[name]
            rep = self.replica_by_name(name)
            assert rep is not None
            self._restarts_used[name] = self._restarts_used.get(name, 0) + 1
            rep.respawn()
            self._restarts.labels(replica=name).inc()
            self._up.labels(replica=name).set(1)
            if rep.crashed_at is not None:
                rec_s = max(0.0, self.clock.now() - rep.crashed_at)
                path = rep.last_spawn_path
                self._recovery.labels(path=path).observe(rec_s)
                info = {"replica": name, "path": path,
                        "recovery_s": rec_s,
                        "adopted": getattr(rep, "adopted_name", None)}
                self.recovery_log.append(info)
                self.last_recovery[name] = info
                rep.crashed_at = None
            restarted.append(rep)
        return restarted

    def poll_liveness(self) -> List[Tuple[str, str]]:
        """Hang-escalation hook: (replica, signal) pairs escalated this
        poll. The in-process fleet has no process to signal — a hung
        thread replica cannot exist on the cooperative scheduler — so
        the base supervisor never escalates; procfleet's
        ProcessSupervisor overrides this with the SIGTERM→SIGKILL
        liveness ladder."""
        return []

    # -- control-plane actuation (ISSUE 20) ----------------------------
    def _make_replica(self, name: str, index: int) -> Replica:
        """Construction hook for late (scale-up) replicas — subclasses
        pre-configure isolation wiring (procfleet sets the process
        injector and standby pool BEFORE the first spawn, so a scale-up
        can adopt a warm spare)."""
        return self.replica_cls(
            name, index, self._server_factory, self.clock, self.injector,
            queue_high_watermark=self.queue_high_watermark,
            itl_slo_s=self.itl_slo_s)

    def spawn_replica(self) -> Replica:
        """Grow the fleet by one replica (controller scale-up). Indices
        never recycle — a drained replica's name stays retired — and the
        newcomer gets the same per-replica gauge/counter initialisation
        as the construction-time set."""
        idx = self._next_index
        self._next_index += 1
        rep = self._make_replica(f"replica{idx}", idx)
        self.replicas.append(rep)
        self._up.labels(replica=rep.name).set(1)
        self._healthy.labels(replica=rep.name).set(1)
        self._crashes.labels(replica=rep.name).inc(0)
        self._restarts.labels(replica=rep.name).inc(0)
        return rep

    def retire_replica(self, replica: Replica) -> None:
        """Terminal, graceful exit (controller scale-down, post-drain):
        the replica leaves the routable set for good — no restart is
        scheduled and its gauges read down. The in-process fleet has no
        process to reap; procfleet's override also shuts the worker
        down and records its exit code."""
        replica.state = "drained"
        self._restart_due.pop(replica.name, None)
        self._up.labels(replica=replica.name).set(0)
        self._healthy.labels(replica=replica.name).set(0)

    def recovery_info(self, name: str) -> Optional[Dict]:
        """The most recent respawn post-mortem for ``name`` (None before
        its first recovery) — the router stamps ``failover`` trace
        events from this."""
        return self.last_recovery.get(name)

    def refresh_health_gauges(self) -> None:
        for rep in self.replicas:
            self._up.labels(replica=rep.name).set(
                1.0 if rep.state == "ready" else 0.0)
            self._healthy.labels(replica=rep.name).set(
                1.0 if rep.health().ready else 0.0)

    def ready_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == "ready"]


def default_server_factory(params, cfg, **server_kwargs):
    """Factory the supervisor calls per replica (and per respawn). Every
    replica keeps a PRIVATE metrics registry — N replicas re-registering
    ``mingpt_serve_*`` in one registry would alias their counters; the
    fleet-level families below live in the shared registry instead."""

    def make(name: str, clock, fault_hook) -> InferenceServer:
        return InferenceServer(
            params, cfg, clock=clock, fault_hook=fault_hook, **server_kwargs)

    return make


# ---------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------

@dataclass
class FleetHandle:
    """Replica-independent view of one routed request. ``tokens`` is the
    caller-visible stream: append-only, deduped across retries."""

    request: Request
    request_id: str
    submit_time: float = 0.0
    deadline: Optional[float] = None
    tokens: List[int] = field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None  # "length" | "eos" | "deadline" | "error"
    error: Optional[BaseException] = None
    attempts: int = 0                    # submissions so far (1 = no retry yet)
    replica: Optional[str] = None        # current / last placement
    duplicates_suppressed: int = 0       # re-emitted token indices dropped
    trace: Optional[TraceContext] = None  # root trace context (ISSUE 10)
    fault_at: Optional[float] = None     # fleet clock when a fault hit us
    recovery_s: Optional[float] = None   # fault -> first NEW token after it
    first_token_at: Optional[float] = None  # fleet clock at first emit (TTFT)


class Router:
    """Health- and affinity-aware request fan-out over a supervised
    replica set, with breakers, bounded retry, shedding and drain."""

    def __init__(
        self,
        supervisor: ReplicaSupervisor,
        on_token: Optional[Callable[[FleetHandle, int], None]] = None,
        affinity_len: int = 16,
        max_retries: int = 2,
        retry_backoff_s: float = 0.05,
        shed_watermark: Optional[int] = None,
        breaker_failure_threshold: int = 3,
        breaker_reset_s: float = 1.0,
        trace_recorder: Optional[TraceRecorder] = None,
        flight: Optional[FlightRecorder] = None,
        admission_policy: Optional[AdmissionPolicy] = None,
    ):
        self.supervisor = supervisor
        # admission ordering over the router's retry/pending queue
        # (ISSUE 12). None keeps the historical FIFO drain exactly; a
        # policy reorders only the entries whose backoff has elapsed.
        # Pass the SAME object to default_server_factory so replica-
        # level slot admission follows the same discipline.
        self.admission_policy = admission_policy
        self.clock = supervisor.clock
        self.on_token = on_token
        self.affinity_len = affinity_len
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.shed_watermark = shed_watermark
        # request-scoped tracing + flight recorder (ISSUE 10). The
        # router mints ONE trace per fleet request at submit; each
        # routed attempt is a fleet.attempt span whose child context
        # rides on the attempt Request into the replica scheduler.
        self.trace_recorder = trace_recorder
        self.flight = flight
        # control plane (ISSUE 20): an attached SLOAutoscaler gets one
        # on_round() per scheduling round; on_finish feeds its signal
        # windows one call per finished fleet request
        self.controller = None
        self.on_finish: Optional[Callable[[FleetHandle, str], None]] = None
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_reset_s = breaker_reset_s
        self._shed_ids = itertools.count()
        if flight is not None:
            # per-replica registry snapshots for crash dumps — lazy
            # closures over the Replica wrapper, so they keep working
            # after a respawn swaps rep.server
            for rep in supervisor.replicas:
                flight.metrics_providers.setdefault(
                    rep.name,
                    (lambda r=rep: render_prometheus(
                        r.server.metrics.registry)))
        self.breakers: Dict[str, CircuitBreaker] = {
            rep.name: CircuitBreaker(
                self.clock.now, breaker_failure_threshold, breaker_reset_s)
            for rep in supervisor.replicas
        }
        self._ids = itertools.count()
        # (replica_name, per-attempt request_id) -> (FleetHandle, RequestHandle)
        self._attempts: Dict[Tuple[str, str], Tuple[FleetHandle, RequestHandle]] = {}
        self._pending: Deque[Tuple[FleetHandle, float]] = deque()
        self.draining = False
        r = supervisor.registry
        self._rejected = r.counter(
            "mingpt_serving_rejected_total",
            help="refused admissions by reason (queue_full | shed | "
                 "breaker_open | deadline | draining)",
            labels=("reason",))
        for reason in ("queue_full", "shed", "breaker_open", "deadline",
                       "draining"):
            self._rejected.labels(reason=reason).inc(0)
        self._requests_total = r.counter(
            "mingpt_fleet_requests_total",
            help="routed requests by terminal outcome",
            labels=("outcome",))
        for outcome in ("completed", "deadline", "error"):
            self._requests_total.labels(outcome=outcome).inc(0)
        self._retries = r.counter(
            "mingpt_fleet_retries_total",
            help="re-submissions onto a surviving replica, by cause",
            labels=("reason",))
        for reason in ("crash", "admit", "error"):
            self._retries.labels(reason=reason).inc(0)
        self._routed = r.counter(
            "mingpt_fleet_routed_total",
            help="placements by affinity outcome (preferred = the prompt-"
                 "head hash replica; spilled = health/load moved it)",
            labels=("affinity",))
        for aff in ("preferred", "spilled"):
            self._routed.labels(affinity=aff).inc(0)
        self._breaker_gauge = r.gauge(
            "mingpt_fleet_breaker_state",
            help="circuit breaker per replica: 0 closed, 1 half-open, "
                 "2 open",
            labels=("replica",))
        self._queue_depth_g = r.gauge(
            "mingpt_fleet_queue_depth",
            help="requests waiting fleet-wide (router retry queue + "
                 "replica queues)")
        self._dup_suppressed = r.counter(
            "mingpt_fleet_duplicate_tokens_suppressed_total",
            help="token indices re-emitted by a retried attempt and "
                 "dropped by the dedup layer (the zero-double-emit "
                 "invariant at work)")
        self._step_failures = r.counter(
            "mingpt_fleet_step_failures_total",
            help="scheduling rounds that raised without killing the "
                 "replica (poisoned rounds; the round's tokens were "
                 "recomputed next round)",
            labels=("replica",))
        self._wire_streaming()
        self._update_gauges()

    # -- wiring ---------------------------------------------------------
    def _wire_streaming(self) -> None:
        for rep in self.supervisor.replicas:
            self._wire_replica(rep)

    def _wire_replica(self, rep: Replica) -> None:
        """Router-side hooks on a (possibly freshly respawned) replica
        server: streaming emitter, shared trace recorder, and the
        watchdog's recompile-triggered flight dump."""
        rep.server.on_token = self._make_emitter(rep.name)
        rep.server.trace_recorder = self.trace_recorder
        if self.flight is not None:
            rep.server.watchdog.on_recompile = (
                lambda grown, name=rep.name: self.flight.dump(
                    "watchdog_recompile", replica=name, families=grown))

    def add_replica(self, rep: Replica) -> None:
        """Wire a freshly spawned (scale-up) replica into the routing
        tier: breaker, streaming emitter + trace recorder, per-replica
        gauges, and the flight recorder's lazy metrics provider —
        everything ``__init__`` did for the construction-time set."""
        self.breakers[rep.name] = CircuitBreaker(
            self.clock.now, self.breaker_failure_threshold,
            self.breaker_reset_s)
        self._wire_replica(rep)
        if self.flight is not None:
            self.flight.metrics_providers.setdefault(
                rep.name,
                (lambda r=rep: render_prometheus(r.server.metrics.registry)))
        self._breaker_gauge.labels(replica=rep.name).set(
            CircuitBreaker.CLOSED)

    def shed_counts(self) -> Dict[str, int]:
        """Cumulative refused admissions by reason — the control
        plane's shed signal (same numbers ``summary()`` reports)."""
        return {labels["reason"]: int(child.value)
                for labels, child in self._rejected.children()}

    def _make_emitter(self, replica_name: str):
        def emit(rh: RequestHandle, token: int) -> None:
            entry = self._attempts.get((replica_name, rh.request_id))
            if entry is None:
                return
            fh, _ = entry
            idx = len(rh.tokens) - 1  # rh.tokens already holds this token
            if idx < len(fh.tokens):
                # a retried attempt re-deriving tokens the caller already
                # saw — greedy determinism makes them identical; drop them
                fh.duplicates_suppressed += 1
                self._dup_suppressed.inc()
                return
            fh.tokens.append(token)
            if fh.first_token_at is None:
                fh.first_token_at = self.clock.now()
            if fh.fault_at is not None:
                # first NEW caller-visible token since a fault hit this
                # request: the recovery tail the chaos sweeps grade
                # (recovery_pNN pools this per-request scalar)
                fh.recovery_s = self.clock.now() - fh.fault_at
                fh.fault_at = None
            # emit events on the FLEET clock, dedup-aware: only tokens
            # that actually reach the caller become events, so a trace's
            # emit count always equals the visible token count
            if self.trace_recorder is not None and fh.trace is not None:
                self.trace_recorder.add_event(
                    fh.trace, "emit", self.clock.now(),
                    token_index=len(fh.tokens) - 1, replica=replica_name)
            if self.on_token is not None:
                self.on_token(fh, token)
        return emit

    # -- placement -------------------------------------------------------
    def _affinity_index(self, prompt) -> int:
        head = np.asarray(list(prompt)[: self.affinity_len], np.uint32)
        return zlib.crc32(head.tobytes()) % len(self.supervisor.replicas)

    def _candidates(self, fh: FleetHandle) -> List[Replica]:
        """Breaker-admitted ready replicas: preferred (affinity) replica
        first when healthy, then healthy by load, then unhealthy-but-
        alive as the last-resort tier. Deterministic: stable sorts,
        index order breaks ties."""
        admitted = [rep for rep in self.supervisor.ready_replicas()
                    if not rep.draining
                    and self.breakers[rep.name].allow()]
        if not admitted:
            return []
        pref_idx = self._affinity_index(fh.request.prompt)
        healthy = [rep for rep in admitted if rep.health().ready]
        degraded = [rep for rep in admitted if not rep.health().ready]
        ordered: List[Replica] = []
        preferred = next((rep for rep in healthy if rep.index == pref_idx),
                         None)
        if preferred is not None:
            healthy.remove(preferred)
            ordered.append(preferred)
        ordered.extend(sorted(healthy, key=lambda rep: rep.load))
        ordered.extend(sorted(degraded, key=lambda rep: rep.load))
        return ordered

    def _attempt_request(self, fh: FleetHandle, rep: Replica) -> bool:
        now = self.clock.now()
        remaining: Optional[float] = None
        if fh.deadline is not None:
            remaining = fh.deadline - now
            if remaining <= 0:
                self._finalize(fh, "deadline")
                return True  # resolved (not placed) — stop trying
        fh.attempts += 1
        # each attempt is a span in the ONE per-request trace; the child
        # context rides on the attempt Request, so every span the
        # replica's scheduler records parents under this attempt
        attempt_ctx: Optional[TraceContext] = fh.trace
        if self.trace_recorder is not None and fh.trace is not None:
            attempt_ctx = self.trace_recorder.open_span(
                fh.trace, "fleet.attempt", now,
                attempt=fh.attempts, replica=rep.name)
        attempt_req = dataclasses.replace(
            fh.request,
            request_id=f"{fh.request_id}-a{fh.attempts}",
            deadline_s=remaining,
            trace=attempt_ctx,
        )
        breaker = self.breakers[rep.name]
        try:
            rh = rep.submit(attempt_req)
        except QueueFullError:
            fh.attempts -= 1  # a full queue is not a failed attempt
            if self.trace_recorder is not None and \
                    attempt_ctx is not fh.trace and attempt_ctx is not None:
                self.trace_recorder.cancel_span(attempt_ctx)
            return False
        except InjectedAdmissionError as e:
            fh.error = e
            if self.trace_recorder is not None and \
                    attempt_ctx is not fh.trace and attempt_ctx is not None:
                self.trace_recorder.close_span(
                    attempt_ctx, self.clock.now(), outcome="admit_error")
                self.trace_recorder.add_event(
                    fh.trace, "retry", self.clock.now(), reason="admit",
                    attempt=fh.attempts)
                self.trace_recorder.mark_forced(fh.trace)
            breaker.record_failure()
            self._retries.labels(reason="admit").inc()
            return False
        breaker.start_probe()
        self._attempts[(rep.name, attempt_req.request_id)] = (fh, rh)
        fh.replica = rep.name
        pref = self._affinity_index(fh.request.prompt) == rep.index
        self._routed.labels(
            affinity="preferred" if pref else "spilled").inc()
        return True

    def _try_route(self, fh: FleetHandle) -> bool:
        for rep in self._candidates(fh):
            if self._attempt_request(fh, rep):
                return True
        return False

    # -- admission -------------------------------------------------------
    def fleet_queue_depth(self) -> int:
        return len(self._pending) + sum(
            len(rep.server.queue) for rep in self.supervisor.ready_replicas())

    def _estimated_wait_s(self) -> float:
        """Backlog × observed mean ITL per ready replica — crude but
        monotone in load, which is all deadline shedding needs."""
        ready = self.supervisor.ready_replicas()
        itls = [rep.server.metrics.itl_mean_s for rep in ready
                if rep.server.metrics.itl_mean_s is not None]
        if not itls:
            return 0.0
        itl = sum(itls) / len(itls)
        return itl * (self.fleet_queue_depth() + 1) / max(1, len(ready))

    def submit(self, request: Request) -> FleetHandle:
        """Route one request. Raises :class:`ShedError` (draining, global
        watermark, unmeetable deadline, every breaker open) instead of
        accepting work the fleet cannot serve. If every candidate replica
        is merely queue-full, the request is accepted and parked in the
        router's retry queue — the global watermark, not per-replica
        queue bounds, is the fleet's admission limit."""
        request.validate()
        now = self.clock.now()
        if self.draining:
            self._rejected.labels(reason="draining").inc()
            self._trace_shed(request, "draining", now)
            raise ShedError("fleet is draining — not accepting new "
                            "requests", reason="draining")
        depth = self.fleet_queue_depth()
        if self.shed_watermark is not None and depth >= self.shed_watermark:
            self._rejected.labels(reason="shed").inc()
            self._trace_shed(request, "shed", now)
            raise ShedError(
                f"fleet queue depth {depth} >= watermark "
                f"{self.shed_watermark} — shedding",
                reason="shed",
                retry_after_s=self._estimated_wait_s() or 0.1)
        if request.deadline_s is not None:
            est = self._estimated_wait_s()
            if est > 0 and request.deadline_s <= est:
                self._rejected.labels(reason="deadline").inc()
                self._trace_shed(request, "deadline", now)
                raise ShedError(
                    f"deadline {request.deadline_s:.3f}s cannot be met: "
                    f"estimated queue wait {est:.3f}s — shedding now "
                    f"instead of expiring later",
                    reason="deadline",
                    retry_after_s=est)
        if not any(self.breakers[rep.name].allow()
                   for rep in self.supervisor.ready_replicas()
                   if not rep.draining):
            self._rejected.labels(reason="breaker_open").inc()
            self._trace_shed(request, "breaker_open", now)
            raise ShedError(
                "every replica's circuit breaker is open — shedding",
                reason="breaker_open",
                retry_after_s=min(
                    (b.reset_after_s for b in self.breakers.values()),
                    default=0.1))
        fh = FleetHandle(
            request=request,
            request_id=f"fleet-{next(self._ids)}",
            submit_time=now,
            deadline=(None if request.deadline_s is None
                      else now + request.deadline_s),
        )
        if self.trace_recorder is not None:
            fh.trace = self.trace_recorder.start_trace(
                fh.request_id, now=now, baggage=trace_baggage(request))
        if not self._try_route(fh):
            # every candidate was queue-full / errored: park for the next
            # round rather than dropping accepted work
            self._pending.append((fh, now + self.retry_backoff_s))
        return fh

    # -- failure handling ------------------------------------------------
    def _trace_shed(self, request: Request, reason: str,
                    now: float) -> None:
        """Shed decisions are traces too (always exported — trouble is
        never sampled away): a tiny trace with one shed event and an
        outcome of "shed"."""
        rec = self.trace_recorder
        if rec is None:
            return
        ctx = rec.start_trace(
            f"fleet-shed-{next(self._shed_ids)}", now=now,
            baggage=trace_baggage(request))
        rec.add_event(ctx, "shed", now, reason=reason)
        rec.end_trace(ctx, now=now, outcome="shed", n_tokens=0,
                      attempts=0, shed_reason=reason)

    def _finalize(self, fh: FleetHandle, reason: str) -> None:
        fh.finished = True
        fh.finish_reason = reason
        outcome = "completed" if reason in ("length", "eos") else reason
        self._requests_total.labels(outcome=outcome).inc()
        if self.on_finish is not None:
            self.on_finish(fh, outcome)
        if self.trace_recorder is not None and fh.trace is not None:
            attrs = {"replica": fh.replica,
                     "duplicates_suppressed": fh.duplicates_suppressed}
            if fh.recovery_s is not None:
                # only fault-touched requests carry the scalar, so an
                # undisturbed run's summaries stay byte-identical
                attrs["recovery_s"] = fh.recovery_s
            self.trace_recorder.end_trace(
                fh.trace, now=self.clock.now(), outcome=reason,
                n_tokens=len(fh.tokens), attempts=fh.attempts, **attrs)

    def _retry_or_fail(self, fh: FleetHandle, reason: str) -> None:
        if fh.attempts > self.max_retries:
            self._finalize(fh, "error")
            return
        self._retries.labels(reason=reason).inc()
        if self.trace_recorder is not None and fh.trace is not None:
            self.trace_recorder.add_event(
                fh.trace, "retry", self.clock.now(), reason=reason,
                attempt=fh.attempts)
            self.trace_recorder.mark_forced(fh.trace)
        backoff = self.retry_backoff_s * (2 ** max(0, fh.attempts - 1))
        self._pending.append((fh, self.clock.now() + backoff))

    def _close_attempt_span(self, fh: FleetHandle, rh: RequestHandle,
                            outcome: str) -> None:
        """Close the fleet.attempt span riding on this attempt's Request
        (must happen before the trace is ended)."""
        if self.trace_recorder is None:
            return
        ctx = rh.request.trace
        if ctx is not None and ctx is not fh.trace:
            self.trace_recorder.close_span(
                ctx, self.clock.now(), outcome=outcome)

    def _resolve_finished(self, replica_name: str, fh: FleetHandle,
                          rh: RequestHandle, crashed: bool) -> None:
        """A replica-level handle finished: translate to fleet outcome."""
        if fh.finished:
            return
        self._close_attempt_span(fh, rh, rh.finish_reason or "unknown")
        if rh.finish_reason in ("length", "eos"):
            fh.replica = replica_name
            self._finalize(fh, rh.finish_reason)
            if not crashed:
                self.breakers[replica_name].record_success()
        elif rh.finish_reason == "deadline":
            self._finalize(fh, "deadline")
        else:  # "error" — on_token raised or replica-internal failure
            fh.error = rh.error or fh.error
            self._retry_or_fail(fh, reason="error")

    def _handle_crash(self, rep: Replica, exc: BaseException) -> None:
        self.breakers[rep.name].trip()
        self.supervisor.mark_crashed(rep)
        victims: List[FleetHandle] = []
        for key in [k for k in self._attempts if k[0] == rep.name]:
            fh, rh = self._attempts.pop(key)
            if rh.finished:
                # retired earlier in this or a previous round — a real
                # completion, even though its server died afterwards
                self._resolve_finished(rep.name, fh, rh, crashed=True)
            elif not fh.finished:
                fh.error = exc
                fh.fault_at = self.clock.now()
                self._close_attempt_span(fh, rh, "crash")
                victims.append(fh)
        for fh in victims:
            self._retry_or_fail(fh, reason="crash")
        if self.flight is not None:
            self.flight.dump("crash", replica=rep.name, error=repr(exc),
                             victims=len(victims))

    def _handle_step_failure(self, rep: Replica, exc: BaseException) -> None:
        """A scheduling round raised without killing the replica (poison).
        Server state is consistent — the fault point sits before any
        per-slot mutation, so the next round recomputes the identical
        decode. Costs a breaker failure; repeated poison opens it."""
        self._step_failures.labels(replica=rep.name).inc()
        breaker = self.breakers[rep.name]
        was_open = breaker.state == CircuitBreaker.OPEN
        breaker.record_failure()
        if (self.flight is not None and not was_open
                and breaker.state == CircuitBreaker.OPEN):
            self.flight.dump("breaker_trip", replica=rep.name,
                             error=repr(exc))

    # -- the scheduling round ---------------------------------------------
    def step(self) -> bool:
        """One fleet round: restarts → re-route retries → step replicas →
        reconcile outcomes → gauges → clock tick. Returns True while any
        routed request is unfinished."""
        now = self.clock.now()
        for name, signal in self.supervisor.poll_liveness():
            # the ladder only SIGNALS the stuck process here; the death
            # is observed — and its requests re-routed — through the
            # ordinary crash path on the next step RPC
            if self.flight is not None:
                self.flight.dump("hang_escalation", replica=name,
                                 signal=signal)
        for rep in self.supervisor.poll_restarts():
            self._wire_replica(rep)
            self.breakers[rep.name].reset_to_probe()
            info = self.supervisor.recovery_info(rep.name)
            if info is not None and self.trace_recorder is not None:
                # failover event spanning dead replica -> its
                # replacement, on every in-flight request the crash
                # re-routed (their retries are still pending here)
                for fh, _ in self._pending:
                    if (fh.replica == rep.name and not fh.finished
                            and fh.trace is not None):
                        self.trace_recorder.add_event(
                            fh.trace, "failover", now,
                            from_replica=rep.name,
                            to_replica=info.get("adopted") or rep.name,
                            path=info["path"],
                            recovery_s=info["recovery_s"])

        if (self._pending
                and not self.supervisor.ready_replicas()
                and not self.supervisor.restarts_scheduled()):
            # nothing will ever serve these — fail loudly, don't spin
            while self._pending:
                fh, _ = self._pending.popleft()
                if not fh.finished:
                    self._finalize(fh, "error")

        still: Deque[Tuple[FleetHandle, float]] = deque()
        if self.admission_policy is None:
            while self._pending:
                fh, not_before = self._pending.popleft()
                if fh.finished:
                    continue
                if fh.deadline is not None and now >= fh.deadline:
                    self._finalize(fh, "deadline")
                    continue
                if now < not_before or not self._try_route(fh):
                    still.append((fh, not_before))
            self._pending = still
        else:
            # policy-ordered drain: entries whose backoff elapsed route
            # in admission order; the rest keep FIFO positions. The
            # policy's on_admit is NOT called here — slot claims happen
            # in the replica scheduler, which counts them.
            ready: List[Tuple[FleetHandle, float]] = []
            while self._pending:
                fh, not_before = self._pending.popleft()
                if fh.finished:
                    continue
                if fh.deadline is not None and now >= fh.deadline:
                    self._finalize(fh, "deadline")
                    continue
                if now < not_before:
                    still.append((fh, not_before))
                else:
                    ready.append((fh, not_before))
            for i in self.admission_policy.order(
                    [fh for fh, _ in ready], now):
                fh, not_before = ready[i]
                if not self._try_route(fh):
                    still.append((fh, not_before))
            self._pending = still

        for rep in self.supervisor.replicas:
            if rep.state != "ready":
                continue
            if not (rep.server.queue or rep.server.slots.occupied):
                continue
            try:
                rep.step()
            except ReplicaCrashed as e:
                self._handle_crash(rep, e)
            except Exception as e:
                self._handle_step_failure(rep, e)

        for key in list(self._attempts.keys()):
            fh, rh = self._attempts.get(key, (None, None))
            if rh is None or not rh.finished:
                continue
            del self._attempts[key]
            self._resolve_finished(key[0], fh, rh, crashed=False)

        if self.controller is not None:
            # control tick AFTER outcomes reconcile (its signal windows
            # see this round's finishes) and BEFORE gauges/clock, so an
            # actuation lands in the same round's exported state
            self.controller.on_round()

        self._update_gauges()
        self.clock.tick()
        return bool(self._pending) or bool(self._attempts)

    def _update_gauges(self) -> None:
        self.supervisor.refresh_health_gauges()
        for name, breaker in self.breakers.items():
            # surface OPEN -> HALF_OPEN transitions that happened purely
            # by clock, not by an allow() call from routing
            breaker.allow()
            self._breaker_gauge.labels(replica=name).set(breaker.state)
        self._queue_depth_g.set(self.fleet_queue_depth())

    # -- drain -----------------------------------------------------------
    def drain(self) -> None:
        """Stop admission (submit() sheds with reason=draining); already-
        accepted work keeps stepping until done."""
        self.draining = True

    def run_until_drained(self, max_steps: Optional[int] = None) -> None:
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"fleet not drained after {max_steps} steps "
                    f"(pending={len(self._pending)}, "
                    f"in_flight={len(self._attempts)})")

    # -- offline convenience ----------------------------------------------
    def generate_batch(self, requests) -> List[FleetHandle]:
        handles = [self.submit(r) for r in requests]
        self.run_until_drained()
        return handles

    def health_report(self) -> Dict[str, Any]:
        """The /healthz payload (ISSUE 10): per-replica breaker state by
        NAME (not the internal int) plus the health-gate reasons the
        routing tier is acting on — what an operator needs to see why a
        replica is being avoided."""
        breaker_names = {CircuitBreaker.CLOSED: "closed",
                         CircuitBreaker.HALF_OPEN: "half_open",
                         CircuitBreaker.OPEN: "open"}
        replicas = {}
        for rep in self.supervisor.replicas:
            h = rep.health()
            replicas[rep.name] = {
                "state": rep.state,
                "breaker": breaker_names[self.breakers[rep.name].state],
                "healthy": h.ready,
                "reasons": h.reasons,
            }
        return {
            "replicas": replicas,
            "draining": self.draining,
            "pending": len(self._pending),
            "in_flight": len(self._attempts),
        }

    # -- fleet-wide observability (ISSUE 13) -------------------------------
    def fleet_metrics_page(self) -> str:
        """One merged Prometheus page for the whole fleet: the shared
        (supervisor/router) registry as-is, plus every live replica's
        PRIVATE registry re-labelled under ``replica=<name>``. Built from
        the Replica wrappers — not captured server objects — so a respawn
        is picked up automatically, exactly like the flight recorder's
        lazy metrics providers."""
        return render_fleet_prometheus(
            self.supervisor.registry,
            {rep.name: rep.server.metrics.registry
             for rep in self.supervisor.replicas},
        )

    def attrib_report(self, include_live: bool = False) -> Dict[str, Any]:
        """Fleet attribution: one ``mingpt-attrib/1`` document per
        replica whose server was built with ``attrib=True``, keyed by
        replica name. Replicas without a ledger are skipped (a fleet may
        mix instrumented and plain servers); raises only when NO replica
        has attribution enabled."""
        replicas = {
            rep.name: rep.server.attrib_report(include_live=include_live)
            for rep in self.supervisor.replicas
            if rep.server.attrib is not None
        }
        if not replicas:
            raise ValueError(
                "no replica has attribution enabled — pass attrib=True "
                "to the server factory")
        return {"schema": "mingpt-attrib-fleet/1", "replicas": replicas}

    def summary(self) -> Dict[str, Any]:
        return {
            "replicas": {
                rep.name: {
                    "state": rep.state,
                    "crashes": rep.crashes,
                    "healthy": rep.health().ready,
                    "health_reasons": rep.health().reasons,
                    "clock_skew_s": rep.clock.skew_s,
                    "breaker_state": self.breakers[rep.name].state,
                    "load": rep.load if rep.state == "ready" else None,
                }
                for rep in self.supervisor.replicas
            },
            "pending": len(self._pending),
            "in_flight": len(self._attempts),
            "draining": self.draining,
            "rejected_by_reason": {
                labels["reason"]: int(child.value)
                for labels, child in self._rejected.children()
            },
            "retries_by_reason": {
                labels["reason"]: int(child.value)
                for labels, child in self._retries.children()
            },
            "requests_by_outcome": {
                labels["outcome"]: int(child.value)
                for labels, child in self._requests_total.children()
            },
            "duplicates_suppressed": int(self._dup_suppressed.value),
        }
