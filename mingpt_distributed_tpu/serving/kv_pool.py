"""Slot-based KV-cache pool for continuous batching.

One fixed ``(n_layer, n_slots, block_size, kv_heads, head_dim)`` pair of
K/V buffers — ``models/generate.init_cache`` with the batch axis
reinterpreted as a *slot* axis. Each slot holds one in-flight request's
cache; a request is admitted by prefilling its prompt into a free slot
(which overwrites the slot's full length, so stale K/V from the previous
tenant can never leak into attention) and retired by returning the slot to
the free list. The buffers themselves never change shape or owner-visible
identity, which is what lets the decode program stay compiled once for the
server's lifetime.

Allocation is deterministic (lowest free index first) so a given arrival
order always produces the same slot placement — the scheduler tests rely
on replayability.
"""

from __future__ import annotations

from typing import List, Optional

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models.generate import Cache, init_cache


class SlotKVPool:
    """Fixed-slot KV cache + host-side free-list.

    The device arrays live in ``.cache`` and are *replaced* (never resized)
    by the engine after each compiled call — jit donation makes the update
    in place at the buffer level while this object keeps a stable handle.
    """

    def __init__(self, cfg: GPTConfig, n_slots: int, dtype=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.cache: Cache = init_cache(cfg, n_slots, dtype)
        self._free: List[int] = list(range(n_slots))  # kept sorted

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.n_slots - len(self._free)

    def allocate(self) -> Optional[int]:
        """Claim the lowest free slot index, or None when exhausted."""
        if not self._free:
            return None
        return self._free.pop(0)

    def free(self, slot: int) -> None:
        """Return a slot to the pool (idempotence is a bug: double-free
        means two requests would share a cache slot, so it raises)."""
        if not (0 <= slot < self.n_slots):
            raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)
        self._free.sort()
