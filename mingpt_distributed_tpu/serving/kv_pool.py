"""Slot-based KV-cache pool + shared-prefix KV store.

One fixed ``(n_layer, n_slots, block_size, kv_heads, head_dim)`` pair of
K/V buffers — ``models/generate.init_cache`` with the batch axis
reinterpreted as a *slot* axis. Each slot holds one in-flight request's
cache; a request is admitted by prefilling its prompt into a free slot
and retired by returning the slot to the free list. Stale K/V from a
previous tenant never leaks into attention because masking is positional
and every writer fills a row with real data before the first query that
could see it (the stale-row invariant, serving/engine.py). The buffers
themselves never change shape or owner-visible identity, which is what
lets the decode program stay compiled once for the server's lifetime.

Allocation is deterministic (lowest free index first) so a given arrival
order always produces the same slot placement — the scheduler tests rely
on replayability.

``PrefixKVStore`` is the byte-bounded LRU behind shared-prefix reuse
(the system-prompt case): entries are device-resident ``(L, 1, P, KV,
hd)`` K/V row blocks keyed by the exact token tuple they encode, with P
quantized to the engine's bucket ladder so the copy programs stay a
bounded compile family. A request whose prompt extends a stored entry
copies its rows instead of recomputing them and prefills only the tail.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models.generate import Cache, init_cache


class SlotKVPool:
    """Fixed-slot KV cache + host-side free-list.

    The device arrays live in ``.cache`` and are *replaced* (never resized)
    by the engine after each compiled call — jit donation makes the update
    in place at the buffer level while this object keeps a stable handle.
    """

    def __init__(self, cfg: GPTConfig, n_slots: int, dtype=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.cache: Cache = init_cache(cfg, n_slots, dtype)
        self._free: List[int] = list(range(n_slots))  # kept sorted

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.n_slots - len(self._free)

    def allocate(self) -> Optional[int]:
        """Claim the lowest free slot index, or None when exhausted."""
        if not self._free:
            return None
        return self._free.pop(0)

    def free(self, slot: int) -> None:
        """Return a slot to the pool (idempotence is a bug: double-free
        means two requests would share a cache slot, so it raises)."""
        if not (0 <= slot < self.n_slots):
            raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)
        self._free.sort()


class PrefixKVStore:
    """Bounded LRU of shared-prefix KV entries.

    Keys are exact token tuples (the prefix the rows encode — hashing the
    tokens themselves, so a hit can never alias two different prefixes);
    values are device-array ``(k, v)`` pairs of shape (L, 1, P, KV, hd)
    with P = len(key). ``capacity_bytes`` bounds the sum of entry sizes;
    inserting past it evicts least-recently-used entries first. An entry
    larger than the whole budget is refused rather than thrashing the
    store empty.
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self.capacity_bytes = capacity_bytes
        self.used_bytes = 0
        self._entries: "OrderedDict[Tuple[int, ...], tuple]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def contains(self, key: Tuple[int, ...]) -> bool:
        return key in self._entries

    @staticmethod
    def _nbytes(kv) -> int:
        return int(kv[0].nbytes) + int(kv[1].nbytes)

    def lookup(self, tokens: Tuple[int, ...]):
        """Longest stored entry that is a *proper* prefix of ``tokens``
        (P < len(tokens): the tail must keep >= 1 token to prefill, since
        the first sampled token needs the last prompt position's logits).
        Returns (rows, (k, v)) or None; a hit refreshes LRU order."""
        best_key = None
        for key in self._entries:
            p = len(key)
            if p < len(tokens) and tokens[:p] == key:
                if best_key is None or p > len(best_key):
                    best_key = key
        if best_key is None:
            return None
        self._entries.move_to_end(best_key)
        return len(best_key), self._entries[best_key]

    def insert(self, key: Tuple[int, ...], kv) -> bool:
        """Store rows for ``key``; evict LRU entries until it fits.
        Returns False when the entry alone exceeds the byte budget or the
        key is already present (refreshed, not replaced — the rows are
        deterministic functions of the tokens, so old is as good as new).
        """
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        need = self._nbytes(kv)
        if need > self.capacity_bytes:
            return False
        while self.used_bytes + need > self.capacity_bytes:
            _, old = self._entries.popitem(last=False)
            self.used_bytes -= self._nbytes(old)
        self._entries[key] = kv
        self.used_bytes += need
        return True
