"""Slot-based KV-cache pool + shared-prefix KV store.

One fixed ``(n_layer, n_slots, block_size, kv_heads, head_dim)`` pair of
K/V buffers — ``models/generate.init_cache`` with the batch axis
reinterpreted as a *slot* axis. Each slot holds one in-flight request's
cache; a request is admitted by prefilling its prompt into a free slot
and retired by returning the slot to the free list. Stale K/V from a
previous tenant never leaks into attention because masking is positional
and every writer fills a row with real data before the first query that
could see it (the stale-row invariant, serving/engine.py). The buffers
themselves never change shape or owner-visible identity, which is what
lets the decode program stay compiled once for the server's lifetime.

Allocation is deterministic (lowest free index first) so a given arrival
order always produces the same slot placement — the scheduler tests rely
on replayability.

Tensor-parallel serving (ISSUE 14): the pool optionally carries a
``NamedSharding`` that splits the KV-heads axis over the mesh's tp axis,
so each device holds ``total / tp`` cache bytes. The sharding is decided
once at construction (it is part of the engine's program identity, see
serving/engine.py) and never changes — the buffers keep the same global
shape, owner-visible identity and host-side free-list semantics whether
they live on one chip or many. Ownership (which slot belongs to which
request) stays a host concept; placement (which chip holds which heads)
is the sharding's concern — the two never interact.

``PrefixKVStore`` is the byte-bounded LRU behind shared-prefix reuse
(the system-prompt case): entries are device-resident ``(L, 1, P, KV,
hd)`` K/V row blocks keyed by the exact token tuple they encode, with P
quantized to the engine's bucket ladder so the copy programs stay a
bounded compile family. A request whose prompt extends a stored entry
copies its rows instead of recomputing them and prefills only the tail.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import List, Optional, Tuple

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models.generate import Cache, init_cache
from mingpt_distributed_tpu.serving import quant as quant_lib


class SlotKVPool:
    """Fixed-slot KV cache + host-side free-list.

    The device arrays live in ``.cache`` and are *replaced* (never resized)
    by the engine after each compiled call — jit donation makes the update
    in place at the buffer level while this object keeps a stable handle.
    """

    def __init__(self, cfg: GPTConfig, n_slots: int, dtype=None,
                 sharding=None, quant=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.quant = quant
        if quant is None:
            cache: Cache = init_cache(cfg, n_slots, dtype)
        else:
            # quantized payload buffers + fp32 scale planes (ISSUE 18);
            # the scale leaves are rank-5 with head_dim -> 1, so the
            # head-sharding spec below applies to them unchanged
            cache = quant_lib.init_quant_cache(cfg, n_slots, quant)
        if sharding is not None:
            import jax

            cache = jax.device_put(
                cache, {name: sharding for name in cache})
            # adopt the runtime's normalized sharding (trailing-None
            # PartitionSpec entries stripped): compiled-program outputs
            # carry the normalized form, and the engine keys executables
            # on sharding equality — an unnormalized spec here would make
            # the first serving call on a warmed bucket look novel
            sharding = cache["k"].sharding
        self.sharding = sharding
        self.cache = cache
        self._free: List[int] = list(range(n_slots))  # kept sorted

    @property
    def shard_count(self) -> int:
        """How many devices one cache buffer is physically split over
        (1 = single-device or replicated — e.g. a kv_heads count the tp
        extent doesn't divide, which shard_by_rule downgrades)."""
        if self.sharding is None:
            return 1
        shape = tuple(self.cache["k"].shape)
        shard = self.sharding.shard_shape(shape)
        return math.prod(shape) // math.prod(shard)

    def audit_facts(self) -> dict:
        """Static facts graftaudit checks pool-touching programs against
        (plain dict so serving never imports the analysis layer):
        ``cache_leaf_elems`` is the element count of one K/V buffer — any
        collective whose result is at least that large is moving the pool
        itself, not a per-token activation; ``cache_sharding`` is the
        runtime-normalized NamedSharding every compiled program must
        return the cache under (None on a single device)."""
        return {
            "cache_leaf_elems": math.prod(tuple(self.cache["k"].shape)),
            "cache_sharding": self.sharding,
            "shard_count": self.shard_count,
        }

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.n_slots - len(self._free)

    def allocate(self) -> Optional[int]:
        """Claim the lowest free slot index, or None when exhausted."""
        if not self._free:
            return None
        return self._free.pop(0)

    def free(self, slot: int) -> None:
        """Return a slot to the pool (idempotence is a bug: double-free
        means two requests would share a cache slot, so it raises)."""
        if not (0 <= slot < self.n_slots):
            raise ValueError(f"slot {slot} outside [0, {self.n_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)
        self._free.sort()


class PrefixKVStore:
    """Bounded LRU of shared-prefix KV entries.

    Keys are exact token tuples (the prefix the rows encode — hashing the
    tokens themselves, so a hit can never alias two different prefixes);
    values are device-array lane dicts (``{"k", "v"}``, plus
    ``{"k_scale", "v_scale"}`` planes when the pool is quantized) of
    shape (L, 1, P, KV, hd) with P = len(key). ``capacity_bytes`` bounds
    the sum of entry sizes across every leaf — a quantized store fits
    ~4x the prefixes in the same budget, which is the ISSUE 18 point;
    inserting past it evicts least-recently-used entries first. An entry
    larger than the whole budget is refused rather than thrashing the
    store empty.
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self.capacity_bytes = capacity_bytes
        self.used_bytes = 0
        self._entries: "OrderedDict[Tuple[int, ...], tuple]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def contains(self, key: Tuple[int, ...]) -> bool:
        return key in self._entries

    def entries(self):
        """(key, lane-dict) pairs in LRU order — read-only introspection
        for accounting and the sharded-serving selftest (which asserts
        stored entries keep the pool's head-sharding instead of
        gathering)."""
        return list(self._entries.items())

    @staticmethod
    def _nbytes(kv) -> int:
        return sum(int(a.nbytes) for a in kv.values())

    def lookup(self, tokens: Tuple[int, ...]):
        """Longest stored entry that is a *proper* prefix of ``tokens``
        (P < len(tokens): the tail must keep >= 1 token to prefill, since
        the first sampled token needs the last prompt position's logits).
        Returns (rows, lane-dict) or None; a hit refreshes LRU order."""
        best_key = None
        for key in self._entries:
            p = len(key)
            if p < len(tokens) and tokens[:p] == key:
                if best_key is None or p > len(best_key):
                    best_key = key
        if best_key is None:
            return None
        self._entries.move_to_end(best_key)
        return len(best_key), self._entries[best_key]

    def insert(self, key: Tuple[int, ...], kv) -> bool:
        """Store rows for ``key``; evict LRU entries until it fits.
        Returns False when the entry alone exceeds the byte budget or the
        key is already present (refreshed, not replaced — the rows are
        deterministic functions of the tokens, so old is as good as new).
        """
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        need = self._nbytes(kv)
        if need > self.capacity_bytes:
            return False
        while self.used_bytes + need > self.capacity_bytes:
            _, old = self._entries.popitem(last=False)
            self.used_bytes -= self._nbytes(old)
        self._entries[key] = kv
        self.used_bytes += need
        return True
