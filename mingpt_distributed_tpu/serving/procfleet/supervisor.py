"""Process-isolated fleet: supervisor, router and live migration over
the procfleet RPC boundary (ISSUE 16 tentpole).

The in-process fleet (``serving/fleet.py``) already has the hard parts —
breakers, bounded retry, token-index dedup, health-gated placement, the
crash→backoff→respawn lifecycle. This module swaps its *failure domain*
from "a Python object we drop" to "an OS process we SIGKILL" without
rewriting any of that machinery:

* :class:`ServerProxy` — duck-types the slice of ``InferenceServer``
  the Router and Replica wrappers actually touch (``queue``, ``slots``,
  ``metrics``, ``watchdog``, ``submit``/``step``, ``on_token``) and
  forwards each call across a :class:`~.transport.SocketTransport` or
  deterministic :class:`~.transport.LoopbackTransport`. The worker is
  **step-driven**: ``step()`` asks the replica for one scheduling round
  and applies the returned event batch to local handle mirrors, so the
  router's round loop, reconcile pass and dedup emitter run verbatim.

* :class:`ProcReplica` — a :class:`~.fleet.Replica` whose ``_spawn``
  produces a backend (subprocess or in-process loopback twin) instead of
  a server object. Liveness is the socket plus the OS: a dead process
  answers its next RPC with a connection error, which ``step()``
  translates to :class:`~.faults.ProcessKilled` (a ``ReplicaCrashed``)
  so the router's crash path — trip breaker, mark crashed, retry victims
  through dedup — applies unchanged. The supervisor additionally reaps
  the corpse: waitpid exit code (negative = signal) and the flight
  recorder dumps left in the dead replica's spill directory.

* :class:`ProcRouter.migrate_and_drain` — live migration. The source
  ships its prefix-store entries and the bucket-quantized leading rows
  of every in-flight slot through the size-framed transfer channel; the
  destination installs them under its own pool sharding (entries stay
  head-sharded on device). In-flight requests re-route from their
  ORIGINAL prompts — the same retry-idempotency invariant that makes
  crash recovery token-exact — so the migrated stream is bit-identical
  while the shipped rows turn the re-prefill into a device-side row
  copy. The drained process exits ``REQUEUE_EXIT_CODE`` (75): the
  scheduler-requeue contract now holds per replica process.

Warm-standby failover (ISSUE 17 tentpole) layers three mechanisms on
top of that machinery without changing its shape:

* :class:`StandbyPool` — N spare workers kept *fully spawned* (params
  restored, program family warmed at worker startup) behind the same
  backend factory. ``ProcReplica._spawn`` adopts a hot spare instead of
  paying spawn + restore + compile, the supervisor collapses the
  restart backoff to "next round" when a spare is waiting, and the
  pool backfills after adoption — off the recovery critical path.

* a supervision escalation ladder — :meth:`ProcessSupervisor.
  poll_liveness` watches per-replica step progress on the injected
  clock; a replica that holds work but completes no round for
  ``hang_deadline_s`` gets SIGTERM, and SIGKILL ``hang_kill_grace_s``
  later if the process is still alive (a worker wedged inside the step
  RPC ignores SIGTERM, like any GIL-held spin). The death is then
  observed through the ordinary crash path, so the replacement routes
  through standby adoption like any other crash.

* speculative-state-complete migration — ``migrate_and_drain`` already
  ships prefix/KV rows; the worker's migrate framing now also carries
  draft-pool rows (head-sharded under tp, lockstep slot mirroring on
  the peer), so a migrated speculative request resumes *proposing*
  without a draft re-prefill (see ``worker.migrate_out_frames``).

Nothing in this module reads the wall clock: fleet time is the injected
clock, process liveness is ``waitpid``, and socket timeouts (an OS I/O
deadline, not a ``time.*`` call) bound real-transport RPCs.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from mingpt_distributed_tpu.serving.fleet import (
    REQUEUE_EXIT_CODE,
    Replica,
    ReplicaHealth,
    ReplicaSupervisor,
    Router,
    SkewedClock,
)
from mingpt_distributed_tpu.serving.procfleet.rpc import (
    EnvelopeError,
    TransportError,
    TransportTimeout,
    envelope,
    request_to_wire,
)
from mingpt_distributed_tpu.serving.procfleet.transport import (
    LoopbackTransport,
    SocketTransport,
)
from mingpt_distributed_tpu.serving.requests import QueueFullError
from mingpt_distributed_tpu.telemetry import (
    MetricsRegistry,
    log_event,
    merge_fleet_pages,
    render_prometheus,
)
from mingpt_distributed_tpu.training.faults import (
    InjectedAdmissionError,
    ProcessFaultInjector,
    ProcessKilled,
    WorkerStuck,
)

__all__ = [
    "ProcReplica",
    "ProcRouter",
    "ProcessBackend",
    "ProcessSupervisor",
    "ReplicaUnreachable",
    "ServerProxy",
    "StandbyPool",
    "LoopbackBackend",
    "loopback_backend_factory",
    "process_backend_factory",
]


class ReplicaUnreachable(InjectedAdmissionError):
    """submit() could not reach the replica process. Subclasses the
    admission-fault type so the router's existing admit-retry path
    (breaker failure + try the next candidate) handles it — the request
    is NOT lost, and the crash is confirmed by the next step RPC."""


# ---------------------------------------------------------------------
# InferenceServer proxy (the duck-typed slice the fleet layer touches)
# ---------------------------------------------------------------------

class _SizedQueue:
    """len()-able stand-in for the worker's request queue."""

    def __init__(self):
        self._n = 0

    def set(self, n: int) -> None:
        self._n = int(n)

    def __len__(self) -> int:
        return self._n


class _ProxySlots:
    occupied = 0


class _ProxyMetrics:
    """Mirrors the two latency numbers health/shedding read, plus an
    empty private registry (renders as an empty page — the real page is
    fetched over /metrics)."""

    def __init__(self):
        self.itl_mean_s: Optional[float] = None
        self.itl_p99_s: Optional[float] = None
        self.registry = MetricsRegistry()


class _ProxyWatchdog:
    def __init__(self):
        self.recompiles = 0
        self.on_recompile = None  # router wires this; fired via step RPC


class ServerProxy:
    """Client half of the step-driven contract: one of these per live
    backend, holding local :class:`RequestHandle` mirrors that the
    router's dedup emitter and reconcile pass consume exactly as they
    would in-process handles."""

    def __init__(self, transport, name: str, clock: Callable[[], float]):
        self.transport = transport
        self.name = name
        self.clock = clock
        self.queue = _SizedQueue()
        self.slots = _ProxySlots()
        self.metrics = _ProxyMetrics()
        self.watchdog = _ProxyWatchdog()
        self.on_token = None          # set by Router._wire_replica
        self.trace_recorder = None    # set by Router._wire_replica (unused:
        #                               the router owns spans and events)
        self.attrib = None            # truthy when the worker has a ledger
        self._handles: Dict[str, Any] = {}
        self._recompiles_seen = 0

    # -- submit ---------------------------------------------------------
    def submit(self, request):
        from mingpt_distributed_tpu.serving.requests import RequestHandle

        doc = envelope("submit", request=request_to_wire(request))
        try:
            resp = self.transport.call("/rpc/submit", doc)
        except TransportError as e:
            raise ReplicaUnreachable(
                f"replica {self.name} unreachable at submit: {e}") from e
        if resp["kind"] == "error":
            err, msg = resp["error"], resp["message"]
            if err == "queue_full":
                raise QueueFullError(
                    msg, queue_depth=resp.get("queue_depth"),
                    retry_after_s=resp.get("retry_after_s"))
            if err in ("admit", "draining"):
                raise InjectedAdmissionError(msg)
            if err == "invalid":
                raise ValueError(msg)
            raise RuntimeError(f"submit to {self.name} failed: {err}: {msg}")
        if resp["kind"] != "submit_result":
            raise EnvelopeError(
                f"submit answered with {resp['kind']!r}")
        rh = RequestHandle(
            request=request,
            request_id=resp["request_id"],
            prompt_used=[int(t) for t in request.prompt],
            max_new_effective=request.max_new_tokens,
            submit_time=self.clock(),
        )
        self._handles[rh.request_id] = rh
        self.queue.set(resp["queue_depth"])
        return rh

    # -- one scheduling round --------------------------------------------
    def step(self) -> bool:
        resp = self.transport.call("/rpc/step", envelope("step"))
        if resp["kind"] == "error":
            # a poisoned round worker-side: replica alive, round lost —
            # surfaces as the router's generic step-failure (breaker
            # failure, recompute next round)
            raise RuntimeError(
                f"step on {self.name} failed: {resp['error']}: "
                f"{resp['message']}")
        if resp["kind"] != "step_result":
            raise EnvelopeError(f"step answered with {resp['kind']!r}")
        now = self.clock()
        for ev in resp["events"]:
            rh = self._handles.get(ev["request_id"])
            if rh is None:
                continue  # finished + reconciled in an earlier round
            if ev["type"] == "emit":
                if ev["token_index"] != len(rh.tokens):
                    raise EnvelopeError(
                        f"{self.name}: emit for {ev['request_id']} at "
                        f"index {ev['token_index']}, expected "
                        f"{len(rh.tokens)} — stream drift across the "
                        f"boundary")
                rh.tokens.append(ev["token"])
                if rh.first_token_time is None:
                    rh.first_token_time = now
                rh.last_token_time = now
                if self.on_token is not None:
                    self.on_token(rh, ev["token"])
            else:  # "finish"
                rh.finished = True
                rh.finish_reason = ev["finish_reason"]
                if ev["finish_reason"] == "error":
                    rh.error = RuntimeError(
                        ev.get("error", "replica-side error"))
                del self._handles[ev["request_id"]]
        self.queue.set(resp["queue_depth"])
        self.slots.occupied = resp["occupied"]
        self.watchdog.recompiles = resp["recompiles"]
        self.metrics.itl_mean_s = resp.get("itl_mean_s")
        self.metrics.itl_p99_s = resp.get("itl_p99_s")
        if (self.watchdog.recompiles > self._recompiles_seen
                and self.watchdog.on_recompile is not None):
            self.watchdog.on_recompile(
                self.watchdog.recompiles - self._recompiles_seen)
        self._recompiles_seen = self.watchdog.recompiles
        return bool(resp["busy"])

    # -- the rest of the surface the fleet layer touches -----------------
    def cancel(self, request_id: str) -> bool:
        resp = self.transport.call(
            "/rpc/cancel", envelope("cancel", request_id=request_id))
        return bool(resp.get("cancelled"))

    def attrib_report(self, include_live: bool = False) -> Dict[str, Any]:
        # live (uncommitted) call spans never cross the boundary — the
        # worker reports committed attribution only
        return self.transport.fetch_json("/attrib")

    def metrics_page(self) -> str:
        return self.transport.fetch_text("/metrics")

    def health_doc(self) -> Dict[str, Any]:
        return self.transport.call("/rpc/health")


# ---------------------------------------------------------------------
# Backends: what "a replica" physically is
# ---------------------------------------------------------------------

class LoopbackBackend:
    """The deterministic twin: a ReplicaWorker held in-process behind
    LoopbackTransport. Same byte-level RPC path, no sockets, no
    processes; kill/term emulate the OS verdicts (-9 / 75) so chaos
    reports are shape-identical across the seam."""

    kind = "loopback"
    pid = None

    def __init__(self, worker, spill_dir: Optional[str] = None,
                 attrib_enabled: bool = False):
        self.worker = worker
        self.transport = LoopbackTransport(worker)
        self.spill_dir = spill_dir
        self.attrib_enabled = attrib_enabled
        self.wedged = False
        self._exit_code: Optional[int] = None

    def alive(self) -> bool:
        return self._exit_code is None

    def mark_wedged(self) -> None:
        """The worker is stuck inside the step RPC. A real wedged worker
        holds the GIL in its signal-handling thread's stead, so SIGTERM's
        Python-level handler never runs — emulate that: only SIGKILL
        (which the OS delivers regardless) clears a wedged loopback."""
        self.wedged = True

    def sigkill(self) -> None:
        if self._exit_code is None:
            self._exit_code = -9
            self.transport.close()

    def sigterm(self) -> None:
        if self.wedged:
            return
        if self._exit_code is None:
            if self.worker.flight is not None:
                self.worker.flight.dump(
                    "drain", replica=self.worker.name,
                    unfinished=len(self.worker.server.unfinished()))
            self._exit_code = REQUEUE_EXIT_CODE
            self.transport.close()

    def wait(self, timeout_s: Optional[float] = None) -> Optional[int]:
        return self._exit_code

    def exit_code(self) -> Optional[int]:
        return self._exit_code

    def spill_dumps(self) -> List[str]:
        if not self.spill_dir:
            return []
        return sorted(glob.glob(os.path.join(self.spill_dir,
                                             "flight-*.json")))


class ProcessBackend:
    """A spawned worker subprocess + its socket transport. Exit codes
    follow waitpid convention: negative is the killing signal (-9 for
    SIGKILL), 75 is the drain/requeue contract."""

    kind = "process"

    def __init__(self, proc: subprocess.Popen, transport: SocketTransport,
                 pid: int, spill_dir: str, attrib_enabled: bool = False):
        self.proc = proc
        self.transport = transport
        self.pid = pid
        self.spill_dir = spill_dir
        self.attrib_enabled = attrib_enabled

    def alive(self) -> bool:
        return self.proc.poll() is None

    def mark_wedged(self) -> None:
        """No-op: a real subprocess wedges worker-side (the worker's own
        injector blocks the step RPC and its SIGTERM handler refuses to
        exit while wedged) — the OS, not this object, decides what
        signals do."""

    def sigkill(self) -> None:
        if self.alive():
            self.proc.kill()

    def sigterm(self) -> None:
        if self.alive():
            self.proc.terminate()

    def wait(self, timeout_s: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None

    def exit_code(self) -> Optional[int]:
        return self.proc.poll()

    def spill_dumps(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.spill_dir,
                                             "flight-*.json")))


def loopback_backend_factory(params, cfg, spill_root: Optional[str] = None,
                             **server_kwargs):
    """Backend factory for the deterministic seam: each spawn builds a
    full in-process InferenceServer (on the replica's SkewedClock, with
    the supervisor's serving-fault hook) wrapped in a ReplicaWorker."""
    from mingpt_distributed_tpu.serving.procfleet.worker import ReplicaWorker
    from mingpt_distributed_tpu.serving.scheduler import InferenceServer

    spawn_counts: Dict[str, int] = {}

    def make(name: str, clock, fault_hook) -> LoopbackBackend:
        n = spawn_counts.get(name, 0)
        spawn_counts[name] = n + 1
        server = InferenceServer(params, cfg, clock=clock,
                                 fault_hook=fault_hook, **server_kwargs)
        flight = None
        spill_dir = None
        if spill_root is not None:
            spill_dir = os.path.join(spill_root, f"{name}-s{n}")
            os.makedirs(spill_dir, exist_ok=True)
            from mingpt_distributed_tpu.telemetry.flightrec import (
                FlightRecorder,
            )
            flight = FlightRecorder(capacity=256, out_dir=spill_dir,
                                    registry=server.metrics.registry)
        worker = ReplicaWorker(server, name=name, flight=flight)
        if flight is not None:
            # same on-disk evidence a real worker leaves at startup, so
            # a SIGKILL'd loopback replica still has a spill to collect
            flight.dump("spawn", replica=name, spawn=n)
        return LoopbackBackend(worker, spill_dir=spill_dir,
                               attrib_enabled=server.attrib is not None)

    return make


def process_backend_factory(spec_base: Dict[str, Any], spill_root: str,
                            rpc_timeout_s: float = 60.0):
    """Backend factory for real isolation: writes the worker spec under a
    per-spawn spill directory, spawns ``python -m ...procfleet.worker``,
    performs the hello handshake on the child's stdout, and binds a
    SocketTransport to the advertised ephemeral port. ``fault_hook`` is
    ignored — serving faults cannot cross the process boundary as
    closures; put them in ``spec_base["serving_faults"]`` and the worker
    builds its own injector."""

    spawn_counts: Dict[str, int] = {}

    def make(name: str, clock, fault_hook) -> ProcessBackend:
        n = spawn_counts.get(name, 0)
        spawn_counts[name] = n + 1
        spill_dir = os.path.join(spill_root, f"{name}-s{n}")
        os.makedirs(spill_dir, exist_ok=True)
        spec = dict(spec_base, name=name, spill_dir=spill_dir)
        spec_path = os.path.join(spill_dir, "spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f, sort_keys=True)
        stderr_path = os.path.join(spill_dir, "stderr.log")
        with open(stderr_path, "wb") as errf:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "mingpt_distributed_tpu.serving.procfleet.worker",
                 spec_path],
                stdout=subprocess.PIPE, stderr=errf, text=True)
        line = proc.stdout.readline()  # blocks until hello or child EOF
        if not line:
            code = proc.wait()
            tail = ""
            try:
                with open(stderr_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"worker {name} died before hello (exit {code}); stderr "
                f"tail:\n{tail}")
        from mingpt_distributed_tpu.serving.procfleet.rpc import (
            validate_envelope,
        )
        hello = validate_envelope(json.loads(line), kind="hello")
        transport = SocketTransport("127.0.0.1", hello["port"],
                                    timeout_s=rpc_timeout_s)
        health = transport.call("/rpc/health")
        return ProcessBackend(proc, transport, pid=hello["pid"],
                              spill_dir=spill_dir,
                              attrib_enabled=bool(health.get("attrib")))

    return make


# ---------------------------------------------------------------------
# StandbyPool
# ---------------------------------------------------------------------

class StandbyPool:
    """N spare workers kept fully spawned behind the same backend
    factory the replicas use — params restored and the program family
    warmed at worker startup, so adoption is a pointer swap plus a
    health probe instead of spawn + restore + compile.

    Each spare owns its :class:`~.fleet.SkewedClock` over the fleet
    clock; the adopting replica takes the clock along with the backend
    (the spare's server was built against it). Spares carry no
    serving-fault hook: round hooks close over a *replica* name, and a
    spare has none until adopted — process-level faults still apply,
    they key on the adopting replica's name at the RPC seam.

    ``fill()`` is synchronous and is called from ``poll_restarts`` —
    AFTER the adoption that emptied the slot — so backfill cost never
    sits on the recovery critical path.
    """

    def __init__(self, factory, fleet_clock, size: int,
                 registry: MetricsRegistry, name_prefix: str = "standby"):
        if size < 1:
            raise ValueError(f"standby pool size must be >= 1, got {size}")
        self.factory = factory
        self.fleet_clock = fleet_clock
        self.size = size
        self.name_prefix = name_prefix
        self._spares: List[Tuple[str, Any, SkewedClock]] = []
        self._spawned = 0
        self._gauge = registry.gauge(
            "mingpt_fleet_standby_pool_size",
            help="pre-warmed spare workers currently available for "
                 "adoption (dips on adoption, restored by backfill)")
        self._adoptions = registry.counter(
            "mingpt_fleet_standby_adoptions_total",
            help="crashed replicas recovered by adopting a hot spare "
                 "instead of a cold respawn")
        self._gauge.set(0)
        self._adoptions.inc(0)
        self.fill()

    def available(self) -> int:
        return len(self._spares)

    def fill(self) -> int:
        """Spawn spares until the pool holds ``size``; returns how many
        were added."""
        added = 0
        while len(self._spares) < self.size:
            name = f"{self.name_prefix}{self._spawned}"
            self._spawned += 1
            clock = SkewedClock(self.fleet_clock.now)
            backend = self.factory(name=name, clock=clock, fault_hook=None)
            self._spares.append((name, backend, clock))
            added += 1
        self._gauge.set(len(self._spares))
        return added

    def acquire(self) -> Optional[Tuple[str, Any, SkewedClock]]:
        """Pop the oldest (warmest) spare, or None when exhausted. Does
        NOT backfill — the caller is mid-recovery."""
        while self._spares:
            name, backend, clock = self._spares.pop(0)
            self._gauge.set(len(self._spares))
            if not backend.alive():
                # a spare that died while idle is not adoptable; skip it
                backend.transport.close()
                continue
            self._adoptions.inc()
            return name, backend, clock
        return None

    def shutdown(self) -> None:
        """Retire every remaining spare (test teardown / end of serving)."""
        for _, backend, _ in self._spares:
            if backend.alive():
                backend.sigterm()
                if backend.wait(timeout_s=10.0) is None:
                    backend.sigkill()
                    backend.wait(timeout_s=10.0)
            backend.transport.close()
        self._spares.clear()
        self._gauge.set(0)


# ---------------------------------------------------------------------
# ProcReplica
# ---------------------------------------------------------------------

class ProcReplica(Replica):
    """A Replica whose server lives behind the RPC boundary. The
    ``server_factory`` contract changes shape: it returns a *backend*
    (LoopbackBackend or ProcessBackend), and the Replica wraps it in a
    ServerProxy — everything above (submit, step, load, health) keeps
    the base types."""

    backend = None
    pinj: Optional[ProcessFaultInjector] = None
    draining = False
    #: set by ProcessSupervisor when a warm pool exists; class default
    #: None means construction-time spawns are always cold
    standby_pool: Optional[StandbyPool] = None
    #: spare identity adopted at the last standby-path spawn
    adopted_name: Optional[str] = None
    #: successfully completed step rounds — the liveness ladder's
    #: progress signal (a wedged replica's count stops advancing)
    steps_ok = 0

    def _spawn(self) -> ServerProxy:
        adopted = (self.standby_pool.acquire()
                   if self.standby_pool is not None else None)
        if adopted is not None:
            spare_name, backend, clock = adopted
            self.backend = backend
            # the spare's server was built against the spare's clock;
            # adopt the clock with it so skew faults keep one timeline
            self.clock = clock
            self.last_spawn_path = "standby"
            self.adopted_name = spare_name
        else:
            if self.standby_pool is not None:
                # a pool was provisioned but had nothing hot: say so
                # loudly — the operator sized it for the fault rate
                log_event(
                    f"[procfleet] standby pool exhausted: cold respawn "
                    f"for {self.name}", file=sys.stderr)
            hook = (self.injector.round_hook(self.name)
                    if self.injector is not None else None)
            self.backend = self._factory(name=self.name, clock=self.clock,
                                         fault_hook=hook)
            self.last_spawn_path = "cold"
            self.adopted_name = None
        proxy = ServerProxy(self.backend.transport, self.name,
                            clock=self.clock)
        if self.backend.attrib_enabled:
            proxy.attrib = True
        return proxy

    def respawn(self) -> None:
        old = self.backend
        if old is not None:
            if old.alive():
                old.sigkill()
                old.wait(timeout_s=10.0)
            old.transport.close()
        if self.pinj is not None:
            # a sticky stuck_step wedge belongs to the dead process, not
            # to the name — the replacement answers its RPCs
            self.pinj.reset(self.name)
        self.draining = False
        super().respawn()

    def step(self) -> bool:
        if self.backend is not None and self.backend.exit_code() is not None:
            # the liveness ladder (or the OS) killed the process between
            # rounds: observe the death BEFORE consulting injectors, or
            # a sticky wedge would mask the crash forever
            raise ProcessKilled(
                f"replica {self.name} process dead before step "
                f"(exit={self.backend.exit_code()})")
        if self.injector is not None:
            # in-process "slow" faults land as clock skew, same as the
            # thread fleet; crash-grade serving faults fire worker-side
            self.clock.skew_s += self.injector.step_delay(self.name)
        if self.pinj is not None:
            try:
                self.clock.skew_s += self.pinj.rpc_verdict(self.name)
            except ProcessKilled:
                # the fault IS the process dying: make it true, then let
                # the crash propagate through the normal path
                self.backend.sigkill()
                self.backend.wait(timeout_s=10.0)
                raise
            except WorkerStuck:
                # the worker wedged inside the step RPC: every later RPC
                # to it times out too, and SIGTERM's handler never runs.
                # Only the supervisor's SIGKILL rung clears it.
                self.backend.mark_wedged()
                raise
            # InjectedHang propagates: replica alive, round lost — the
            # router's step-failure path records a breaker failure
        try:
            busy = self.server.step()
        except TransportTimeout:
            raise  # lost round, process presumed alive
        except TransportError as e:
            self.backend.wait(timeout_s=10.0)
            raise ProcessKilled(
                f"replica {self.name} process died mid-step "
                f"(exit={self.backend.exit_code()}): {e}") from e
        self.steps_ok += 1
        return busy

    def health(self) -> ReplicaHealth:
        if self.state == "drained":
            return ReplicaHealth(False, ["drained"])
        h = super().health()
        if self.draining and h.ready:
            return ReplicaHealth(False, ["draining"])
        return h

    def reap(self) -> Dict[str, Any]:
        """Post-mortem of the current backend: exit code (waitpid
        convention) + the flight-recorder dumps the dead worker spilled."""
        b = self.backend
        if b is None:
            return {}
        if b.alive():
            b.wait(timeout_s=10.0)
        return {"backend": b.kind, "pid": b.pid,
                "exit_code": b.exit_code(),
                "spill_dumps": b.spill_dumps()}

    def shutdown(self, timeout_s: float = 10.0) -> Dict[str, Any]:
        """Graceful retirement: SIGTERM, wait for the requeue exit (75),
        escalate to SIGKILL only if the worker ignores the contract."""
        b = self.backend
        if b is None:
            return {}
        b.sigterm()
        code = b.wait(timeout_s=timeout_s)
        if code is None:
            b.sigkill()
            b.wait(timeout_s=timeout_s)
        b.transport.close()
        return {"backend": b.kind, "pid": b.pid,
                "exit_code": b.exit_code(),
                "spill_dumps": b.spill_dumps()}


# ---------------------------------------------------------------------
# ProcessSupervisor
# ---------------------------------------------------------------------

class ProcessSupervisor(ReplicaSupervisor):
    """ReplicaSupervisor over ProcReplica: the same backoff/budget
    lifecycle, plus OS-level crash forensics (exit codes, spill dumps),
    the process-restart / migration counters, and — when provisioned —
    the warm-standby pool and the hang-escalation liveness ladder.

    ``standby=N`` keeps N spares hot; a crash whose restart the budget
    allows is then rescheduled for *now* (adoption needs no backoff —
    the spare is already serving-ready) and ``poll_restarts`` backfills
    the pool afterwards. ``hang_deadline_s`` arms the ladder: a replica
    holding work that completes no round for that long (fleet clock)
    gets SIGTERM; if the process is still alive ``hang_kill_grace_s``
    later — a wedged worker ignores SIGTERM — it gets SIGKILL, and the
    death recovers through the ordinary crash path."""

    replica_cls = ProcReplica

    def __init__(self, backend_factory, n_replicas: int = 2, clock=None,
                 injector=None, process_injector=None, registry=None,
                 standby: int = 0,
                 hang_deadline_s: Optional[float] = None,
                 hang_kill_grace_s: float = 0.05,
                 **kwargs):
        super().__init__(backend_factory, n_replicas=n_replicas,
                         clock=clock, injector=injector,
                         registry=registry, **kwargs)
        self.process_injector = process_injector
        for rep in self.replicas:
            rep.pinj = process_injector
        self.hang_deadline_s = hang_deadline_s
        self.hang_kill_grace_s = hang_kill_grace_s
        #: replica -> {count, since, term_at}: step progress watermarks
        self._liveness: Dict[str, Dict[str, Any]] = {}
        r = self.registry
        self._proc_restarts = r.counter(
            "mingpt_fleet_process_restarts_total",
            help="replica worker processes respawned after a process "
                 "death (subset of mingpt_fleet_restarts_total where the "
                 "failure domain was the OS process)",
            labels=("replica",))
        self._migrations = r.counter(
            "mingpt_fleet_migrations_total",
            help="live KV/prefix migrations by outcome (ok = state "
                 "shipped and installed; failed = transfer failed, "
                 "requests still recovered by plain re-route)",
            labels=("outcome",))
        self._hang_esc = r.counter(
            "mingpt_fleet_hang_escalations_total",
            help="stuck-replica escalations by signal: term = polite "
                 "SIGTERM at the liveness deadline, kill = SIGKILL after "
                 "the grace window with the process still alive",
            labels=("signal",))
        for rep in self.replicas:
            self._proc_restarts.labels(replica=rep.name).inc(0)
        for outcome in ("ok", "failed"):
            self._migrations.labels(outcome=outcome).inc(0)
        for sig in ("term", "kill"):
            self._hang_esc.labels(signal=sig).inc(0)
        self.standby_pool: Optional[StandbyPool] = None
        if standby > 0:
            self.standby_pool = StandbyPool(
                backend_factory, self.clock, standby, r)
            for rep in self.replicas:
                rep.standby_pool = self.standby_pool
        #: post-mortems collected at mark_crashed time, in crash order
        self.crash_reports: List[Dict[str, Any]] = []
        #: replica name -> exit code recorded at graceful retirement
        self.drained_exits: Dict[str, Optional[int]] = {}

    def mark_crashed(self, replica) -> None:
        super().mark_crashed(replica)
        self._liveness.pop(replica.name, None)
        if (self.standby_pool is not None
                and self.standby_pool.available() > 0
                and replica.name in self._restart_due):
            # a hot spare is waiting: adoption needs no cold-spawn
            # backoff, so the replacement serves on the next round (the
            # restart *budget* still applies — the base scheduled this)
            self._restart_due[replica.name] = self.clock.now()
        self.crash_reports.append(
            {"replica": replica.name, **replica.reap()})

    def poll_restarts(self):
        restarted = super().poll_restarts()
        for rep in restarted:
            self._proc_restarts.labels(replica=rep.name).inc()
        if restarted and self.standby_pool is not None:
            # backfill AFTER the adoptions above — the spawn cost lands
            # here, not on the crash->serving window just recorded
            self.standby_pool.fill()
        return restarted

    def poll_liveness(self) -> List[Tuple[str, str]]:
        """The escalation ladder, driven once per router round on the
        injected clock. Progress = ``steps_ok`` advancing; only replicas
        that hold work are judged (the router does not step idle
        replicas, so an idle stall is not a hang). Returns the
        ``(replica, signal)`` escalations fired this poll."""
        escalated: List[Tuple[str, str]] = []
        if self.hang_deadline_s is None:
            return escalated
        now = self.clock.now()
        for rep in self.replicas:
            if (rep.state != "ready" or rep.backend is None
                    or rep.load == 0):
                self._liveness.pop(rep.name, None)
                continue
            if rep.backend.exit_code() is not None:
                continue  # already dead; the crash path observes it next
            st = self._liveness.get(rep.name)
            if st is None or rep.steps_ok != st["count"]:
                self._liveness[rep.name] = {
                    "count": rep.steps_ok, "since": now, "term_at": None}
                continue
            if st["term_at"] is None:
                if now - st["since"] >= self.hang_deadline_s:
                    rep.backend.sigterm()
                    st["term_at"] = now
                    self._hang_esc.labels(signal="term").inc()
                    escalated.append((rep.name, "term"))
            elif now - st["term_at"] >= self.hang_kill_grace_s:
                # grace expired with the process still alive: the worker
                # ignored SIGTERM (wedged inside the step RPC) — SIGKILL
                # is not ignorable
                rep.backend.sigkill()
                rep.backend.wait(timeout_s=10.0)
                self._hang_esc.labels(signal="kill").inc()
                escalated.append((rep.name, "kill"))
                self._liveness.pop(rep.name, None)
        return escalated

    # -- control-plane actuation (ISSUE 20) ----------------------------
    def _make_replica(self, name: str, index: int):
        """Scale-up construction with the process wiring in place
        BEFORE the first spawn: the injector so chaos reaches the
        newcomer, and the standby pool so a scale-up adopts a hot spare
        (warm path) instead of paying a cold worker spawn when one is
        waiting."""
        rep = self.replica_cls.__new__(self.replica_cls)
        rep.pinj = self.process_injector
        if self.standby_pool is not None:
            rep.standby_pool = self.standby_pool
        rep.__init__(
            name, index, self._server_factory, self.clock, self.injector,
            queue_high_watermark=self.queue_high_watermark,
            itl_slo_s=self.itl_slo_s)
        return rep

    def spawn_replica(self):
        rep = super().spawn_replica()
        self._proc_restarts.labels(replica=rep.name).inc(0)
        if self.standby_pool is not None:
            # backfill after a possible adoption, same ordering contract
            # as poll_restarts: the spawn cost lands here, not on the
            # scale-up decision's latency
            self.standby_pool.fill()
        return rep

    def retire_replica(self, replica) -> Dict[str, Any]:
        """Graceful, terminal shutdown (post-migration): the replica
        leaves the routable set for good — no restart is scheduled, and
        its exit code (75 per the requeue contract) is recorded."""
        info = replica.shutdown()
        self.drained_exits[replica.name] = info.get("exit_code")
        replica.state = "drained"
        self._restart_due.pop(replica.name, None)
        self._up.labels(replica=replica.name).set(0)
        self._healthy.labels(replica=replica.name).set(0)
        return info

    def shutdown_all(self) -> Dict[str, Optional[int]]:
        """Terminate every live backend — replicas AND unadopted spares
        (end of serving / test teardown)."""
        for rep in self.replicas:
            if rep.state != "drained" and rep.backend is not None \
                    and rep.backend.alive():
                info = rep.shutdown()
                self.drained_exits.setdefault(
                    rep.name, info.get("exit_code"))
        if self.standby_pool is not None:
            self.standby_pool.shutdown()
        return dict(self.drained_exits)


# ---------------------------------------------------------------------
# ProcRouter
# ---------------------------------------------------------------------

class ProcRouter(Router):
    """Router over a ProcessSupervisor. Placement additionally skips
    draining replicas; fleet observability is fetched over the RPC
    surface (a subprocess's private registry is not importable); and
    ``migrate_and_drain`` implements live migration."""

    def _candidates(self, fh):
        return [rep for rep in super()._candidates(fh)
                if not getattr(rep, "draining", False)]

    def fleet_metrics_page(self) -> str:
        """Merged Prometheus page: the shared supervisor/router registry
        plus every live replica's /metrics page fetched over RPC and
        re-labelled under ``replica=<name>`` — ONE TYPE line per family,
        same output contract as the in-process fleet page."""
        pages: Dict[str, str] = {}
        for rep in self.supervisor.replicas:
            if rep.state != "ready" or rep.backend is None:
                continue
            try:
                pages[rep.name] = rep.backend.transport.fetch_text(
                    "/metrics")
            except TransportError:
                continue  # dying replica: its crash path will run next
        return merge_fleet_pages(
            render_prometheus(self.supervisor.registry), pages)

    def export_migrate_blob(self, src) -> bytes:
        """Fetch ``src``'s size-framed migration blob (KV/prefix/draft
        state) over RPC. Building block shared with the cross-host
        :class:`~.hostplane.CrossHostRouter`, which pushes the same blob
        through a :class:`~.hostplane.PacedChannel` instead of a direct
        POST."""
        return src.backend.transport.fetch_bytes("/rpc/migrate_out")

    def install_migrate_blob(self, dst, blob: bytes) -> Dict[str, Any]:
        """Install a migration blob into ``dst``; returns the validated
        ``migrate_in_result`` envelope (raises EnvelopeError on a
        mismatched answer)."""
        resp = dst.backend.transport.post_bytes("/rpc/migrate_in", blob)
        if resp.get("kind") != "migrate_in_result":
            raise EnvelopeError(
                f"migrate_in answered with {resp.get('kind')!r}: "
                f"{resp.get('message')}")
        return resp

    def detach_unfinished(self, src_name: str,
                          to_label: str = "") -> List[Any]:
        """Pop every in-flight attempt off ``src_name``: finished ones
        resolve normally, unfinished ones get their attempt span closed
        as ``migrated`` (plus a trace event) and are returned for the
        caller to re-queue — locally into ``_pending`` or on another
        host entirely. The handles are NOT re-queued here."""
        now = self.clock.now()
        unfinished: List[Any] = []
        for key in [k for k in self._attempts if k[0] == src_name]:
            fh, rh = self._attempts.pop(key)
            if rh.finished:
                self._resolve_finished(src_name, fh, rh, crashed=False)
                continue
            self._close_attempt_span(fh, rh, "migrated")
            if self.trace_recorder is not None and fh.trace is not None:
                self.trace_recorder.add_event(
                    fh.trace, "migrate", now,
                    from_replica=src_name, to_replica=to_label)
                self.trace_recorder.mark_forced(fh.trace)
            unfinished.append(fh)
        return unfinished

    def drain_and_retire(self, src) -> Dict[str, Any]:
        """Best-effort drain envelope, then terminal retirement (exit
        75 per the requeue contract). Returns the shutdown info."""
        try:
            src.backend.transport.call(
                "/rpc/drain", envelope("drain", migrate=True))
        except TransportError:
            pass  # already unreachable; retirement reaps it either way
        info = self.supervisor.retire_replica(src)
        self._update_gauges()
        return info

    def migrate_and_drain(self, src_name: str,
                          dst_name: Optional[str] = None) -> Dict[str, Any]:
        """Drain ``src_name`` with zero loss: ship its prefix/KV state to
        a peer, re-route every in-flight request (bit-identical streams
        via the retry-idempotency invariant + dedup), then retire the
        source process (exit 75). Returns a ``mingpt-migrate/1`` report.

        A failed transfer degrades, never loses: the counter records
        ``outcome="failed"`` and the in-flight requests still re-route —
        they merely re-prefill from scratch on the peer."""
        src = self.supervisor.replica_by_name(src_name)
        if src is None or src.state != "ready":
            raise ValueError(
                f"cannot migrate from {src_name!r}: not a ready replica")
        src.draining = True  # no new placements while state ships
        if dst_name is not None:
            dst = self.supervisor.replica_by_name(dst_name)
        else:
            peers = [r for r in self.supervisor.ready_replicas()
                     if r.name != src_name
                     and not getattr(r, "draining", False)]
            dst = min(peers, key=lambda r: (r.load, r.index),
                      default=None)
        if dst is None or dst.state != "ready" or dst.name == src_name:
            src.draining = False
            raise ValueError(
                f"no migration destination for {src_name!r}")
        now = self.clock.now()
        outcome, installed, skipped, error = "ok", 0, 0, None
        draft_installed = 0
        try:
            blob = self.export_migrate_blob(src)
            resp = self.install_migrate_blob(dst, blob)
            installed = resp["installed"]
            skipped = resp["skipped"]
            draft_installed = resp.get("draft_installed", 0)
        except (TransportError, EnvelopeError) as e:
            outcome, error = "failed", repr(e)
        self.supervisor._migrations.labels(outcome=outcome).inc()
        # re-route every in-flight attempt from its ORIGINAL prompt; the
        # dedup emitter suppresses indices the caller already saw, so
        # the visible stream stays append-only and token-exact
        moved: List[str] = []
        for fh in self.detach_unfinished(src_name, to_label=dst.name):
            self._pending.append((fh, now))
            moved.append(fh.request_id)
        info = self.drain_and_retire(src)
        report = {
            "schema": "mingpt-migrate/1",
            "from": src_name,
            "to": dst.name,
            "outcome": outcome,
            "error": error,
            "entries_installed": installed,
            "entries_skipped": skipped,
            "draft_rows_installed": draft_installed,
            "requests_moved": sorted(moved),
            "src_exit_code": info.get("exit_code"),
        }
        if self.flight is not None:
            self.flight.dump("migration",
                             **{k: v for k, v in report.items()
                                if k != "schema"})
        return report
