"""Replica worker: one ``InferenceServer`` behind the ``mingpt-rpc/1``
surface (ISSUE 16).

:class:`ReplicaWorker` is the transport-agnostic core — a dispatch table
from (method, path, body) to envelope responses — used directly by the
deterministic loopback transport and wrapped by :class:`RpcHttpServer`
(seeded from the TelemetryServer stack: stdlib ``ThreadingHTTPServer``,
daemon threads, ``port=0`` ephemeral bind) when this module runs as a
spawned subprocess (``python -m
mingpt_distributed_tpu.serving.procfleet.worker <spec.json>``).

Endpoints::

    POST /rpc/submit       submit envelope  -> submit_result | error
    POST /rpc/step         one scheduling round -> step_result (events)
    GET  /rpc/stream?request_id=ID   chunked stream_token lines
    POST /rpc/cancel       -> cancel_result
    POST /rpc/drain        -> drain_result (stops admission)
    GET  /rpc/health       -> health envelope
    GET  /rpc/migrate_out  -> size-framed KV/prefix blob (octet-stream)
    POST /rpc/migrate_in   size-framed blob -> migrate_in_result
    GET  /metrics          Prometheus text page (private registry)
    GET  /attrib           mingpt-attrib/1 JSON (404 without a ledger)

**Step-driven contract.** The worker never decodes on its own: each
``/rpc/step`` runs exactly one scheduling round and returns the round's
emitted tokens (with explicit ``token_index``) and finish verdicts as an
event batch. The router stays in control of rounds over both transports,
which is what makes a kill -9 equivalent to the in-process crash the
retry/dedup machinery was built against: a step whose response never
arrives loses that round's events — tokens are *lost, never duplicated*
— and the retried attempt regenerates them deterministically while the
router's token-index dedup suppresses the prefix the caller already saw.
The chunked ``/rpc/stream`` endpoint is fed from the same per-request
buffers as rounds complete, so real-socket callers can watch a token
stream live without changing the round contract.

**Migration.** ``/rpc/migrate_out`` ships every prefix-store entry plus
the bucket-quantized leading prompt rows of every in-flight slot
(extracted through the engine's compiled row-copy program — rows stay on
the ladder, the bounded-program family never grows) through the
size-framed transfer channel. ``/rpc/migrate_in`` installs entries into
the peer's prefix store re-placed under its pool sharding, so entries
stay head-sharded on device. Generated-token rows are intentionally NOT
shipped: a migrated request re-admits from its original prompt (the
retry-idempotency invariant), hits the migrated prefix entry as a
device-side row copy, and re-derives any decoded suffix deterministically
under the router's dedup — zero admitted requests lost, zero duplicate
emissions, bit-identical stream.

With speculation on, migration is *state-complete* (ISSUE 17): the
blob also carries ``draft_rows`` frames — the bucket-quantized leading
prompt rows of each in-flight slot's DRAFT pool, head-sharded under tp
exactly like target rows. The peer parks them keyed by prompt prefix;
when the migrated request re-admits (lockstep slot mirroring assigns a
fresh draft slot), ``SpeculativeDecoder.prime`` adopts the parked rows
as a device-side row copy and the request resumes *proposing* without
a draft re-prefill.

**Hangs.** A ``stuck_step`` process fault wedges the worker INSIDE the
step RPC while holding the dispatch lock: the RPC never answers, every
later RPC times out behind the lock, and the SIGTERM handler refuses
to exit while wedged (a real wedge — a C loop holding the GIL — never
runs the Python handler at all). Only SIGKILL, the supervisor's second
escalation rung, clears the process.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from mingpt_distributed_tpu.serving.procfleet.rpc import (
    EnvelopeError,
    envelope,
    pack_frames,
    request_from_wire,
    unpack_frames,
    validate_envelope,
)
from mingpt_distributed_tpu.serving.requests import QueueFullError
from mingpt_distributed_tpu.training.faults import (
    InjectedAdmissionError,
    InjectedServingFault,
    ProcessKilled,
    WorkerStuck,
)

__all__ = ["ReplicaWorker", "RpcHttpServer", "main"]


def _json_body(doc: Dict[str, Any]) -> bytes:
    return json.dumps(doc, sort_keys=True).encode()


def _error(status: int, error: str, message: str,
           **extra: Any) -> Tuple[int, str, bytes]:
    return (status, "application/json",
            _json_body(envelope("error", error=error, message=message,
                                **extra)))


class ReplicaWorker:
    """One InferenceServer behind the RPC dispatch table. Thread-safe:
    the HTTP server is threaded, so every server mutation happens under
    one lock; the stream endpoint waits on a condition fed by the same
    emit path and never holds the lock while blocked."""

    def __init__(self, server, name: str = "replica", flight=None,
                 pinj=None):
        self.server = server
        self.name = name
        self.flight = flight
        self.pinj = pinj  # worker-side ProcessFaultInjector (or None)
        #: set when a stuck_step fault wedged this worker — main()'s
        #: SIGTERM handler consults it to model an unkillable wedge
        self.wedged = threading.Event()
        self.draining = False
        self._lock = threading.RLock()
        # round event batch (drained by each step RPC)
        self._events: List[Dict[str, Any]] = []
        self._tracked: Dict[str, Any] = {}
        self._finish_reported: set = set()
        # per-request live stream buffers for /rpc/stream
        self._stream_cv = threading.Condition()
        self._streams: Dict[str, Dict[str, Any]] = {}
        server.on_token = self._on_token

    # -- emit plumbing --------------------------------------------------
    def _on_token(self, rh, token: int) -> None:
        idx = len(rh.tokens) - 1  # rh.tokens already holds this token
        ev = {"type": "emit", "request_id": rh.request_id,
              "token": int(token), "token_index": idx}
        self._events.append(ev)
        with self._stream_cv:
            buf = self._streams.setdefault(
                rh.request_id, {"tokens": [], "finish": None})
            buf["tokens"].append((idx, int(token)))
            self._stream_cv.notify_all()

    def _note_finishes(self) -> None:
        for rid, h in list(self._tracked.items()):
            if not h.finished or rid in self._finish_reported:
                continue
            self._finish_reported.add(rid)
            reason = h.finish_reason or "error"
            ev = {"type": "finish", "request_id": rid,
                  "finish_reason": reason, "n_tokens": len(h.tokens)}
            if h.error is not None:
                ev["error"] = repr(h.error)
            self._events.append(ev)
            if self.flight is not None:
                self.flight.record("request_finish", dict(
                    ts=self.server.clock(), request_id=rid, reason=reason,
                    n_tokens=len(h.tokens)))
            with self._stream_cv:
                buf = self._streams.setdefault(
                    rid, {"tokens": [], "finish": None})
                buf["finish"] = reason
                self._stream_cv.notify_all()

    # -- endpoint bodies ------------------------------------------------
    def _submit(self, doc: Dict[str, Any]) -> Tuple[int, str, bytes]:
        if self.draining:
            return _error(503, "draining",
                          f"replica {self.name} is draining")
        request = request_from_wire(doc["request"])
        try:
            with self._lock:
                rh = self.server.submit(request)
        except QueueFullError as e:
            return _error(429, "queue_full", str(e),
                          queue_depth=e.queue_depth,
                          retry_after_s=e.retry_after_s)
        except InjectedAdmissionError as e:
            return _error(503, "admit", str(e))
        except ValueError as e:
            return _error(400, "invalid", str(e))
        self._tracked[rh.request_id] = rh
        if self.flight is not None:
            self.flight.record("request_submit", dict(
                ts=self.server.clock(), request_id=rh.request_id,
                prompt_len=len(rh.prompt_used)))
        return (200, "application/json", _json_body(envelope(
            "submit_result", request_id=rh.request_id,
            queue_depth=len(self.server.queue))))

    def _maybe_process_fault(self) -> None:
        """Worker-side process faults, consulted inside the step RPC
        while the dispatch lock is held. ``stuck_step`` wedges: the RPC
        thread blocks forever on a never-set event WITH the lock, so
        this response and every later RPC time out at the client —
        exactly the sticky client-side (loopback) semantics. ``kill``
        makes the fault true: the process SIGKILLs itself mid-RPC."""
        if self.pinj is None:
            return
        try:
            self.pinj.rpc_verdict(self.name)
        except WorkerStuck:
            self.wedged.set()
            if self.flight is not None:
                self.flight.dump("stuck_step", replica=self.name,
                                 pid=os.getpid())
            threading.Event().wait()  # the wedge: never returns
        except ProcessKilled:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    def _step(self) -> Tuple[int, str, bytes]:
        with self._lock:
            self._maybe_process_fault()
            try:
                busy = self.server.step()
            except InjectedServingFault as e:
                # a poisoned round: server state is consistent (the fault
                # point sits before any per-slot mutation) — report the
                # failure, keep the process alive
                self._events.clear()
                return _error(500, "step_failure", repr(e))
            self._note_finishes()
            events, self._events = self._events, []
            m = self.server.metrics
            doc = envelope(
                "step_result", events=events,
                queue_depth=len(self.server.queue),
                occupied=self.server.slots.occupied,
                recompiles=self.server.watchdog.recompiles,
                busy=bool(busy),
                itl_mean_s=m.itl_mean_s, itl_p99_s=m.itl_p99_s)
        return (200, "application/json", _json_body(doc))

    def _cancel(self, doc: Dict[str, Any]) -> Tuple[int, str, bytes]:
        with self._lock:
            ok = self.server.cancel(doc["request_id"])
            self._note_finishes()
        return (200, "application/json",
                _json_body(envelope("cancel_result", cancelled=bool(ok))))

    def _drain(self, doc: Dict[str, Any]) -> Tuple[int, str, bytes]:
        with self._lock:
            self.draining = True
            unfinished = len(self.server.unfinished())
            if self.flight is not None:
                self.flight.dump("drain", replica=self.name,
                                 unfinished=unfinished,
                                 migrate=bool(doc["migrate"]))
        return (200, "application/json", _json_body(envelope(
            "drain_result", draining=True, unfinished=unfinished)))

    def _health(self) -> Tuple[int, str, bytes]:
        with self._lock:
            m = self.server.metrics
            doc = envelope(
                "health",
                queue_depth=len(self.server.queue),
                occupied=self.server.slots.occupied,
                draining=self.draining,
                recompiles=self.server.watchdog.recompiles,
                pid=os.getpid(),
                itl_mean_s=m.itl_mean_s, itl_p99_s=m.itl_p99_s,
                attrib=self.server.attrib is not None)
        return (200, "application/json", _json_body(doc))

    def _metrics(self) -> Tuple[int, str, bytes]:
        from mingpt_distributed_tpu.telemetry import render_prometheus
        with self._lock:
            page = render_prometheus(self.server.metrics.registry)
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                page.encode())

    def _attrib(self) -> Tuple[int, str, bytes]:
        if self.server.attrib is None:
            return _error(404, "no_attrib",
                          "no attribution ledger configured")
        with self._lock:
            doc = self.server.attrib_report()
        return (200, "application/json",
                json.dumps(doc, sort_keys=True).encode())

    # -- migration ------------------------------------------------------
    def migrate_out_frames(self) -> List[Tuple[Dict[str, Any], bytes]]:
        """Everything a peer needs to take over this replica's KV reuse
        state: all prefix-store entries + the shippable leading rows of
        every in-flight slot, as transfer-channel frames."""
        import jax

        def entry_frame(kind: str, key, entry: Dict[str, Any]):
            # per-leaf manifest: a quantized lane ships payload leaves +
            # scale planes (int8 payload bytes are what make migration
            # ~4x cheaper, ISSUE 18); an fp32 lane ships just k/v. The
            # payload is each leaf's raw bytes concatenated in manifest
            # order.
            leaves = []
            blobs = []
            for name in sorted(entry):
                arr = np.asarray(jax.device_get(entry[name]))
                leaves.append({"name": name, "dtype": str(arr.dtype),
                               "shape": list(arr.shape),
                               "nbytes": int(arr.nbytes)})
                blobs.append(arr.tobytes())
            meta = {"type": kind, "key": [int(t) for t in key],
                    "leaves": leaves}
            return meta, b"".join(blobs)

        eng = self.server.engine
        spec_dec = getattr(self.server, "spec", None)
        frames: List[Tuple[Dict[str, Any], bytes]] = []
        shipped = set()
        draft_shipped = set()
        if eng.prefix_store is not None:
            for key, entry in eng.prefix_store.entries():
                frames.append(entry_frame("prefix_entry", key, entry))
                shipped.add(tuple(key))
        for h in self.server.slots.live_handles():
            if h.finished or h.slot is None:
                continue
            frontier = (h.prefill_pos if h.prefilling
                        else len(h.prompt_used))
            rows = eng.migratable_rows(len(h.prompt_used), frontier)
            if rows > 0:
                key = tuple(int(t) for t in h.prompt_used[:rows])
                if key not in shipped:
                    entry = eng.extract_slot_rows(h.slot, rows)
                    frames.append(entry_frame("slot_rows", key, entry))
                    shipped.add(key)
            if spec_dec is None or h.prefilling:
                continue
            # state-complete speculation: ship the DRAFT pool's leading
            # prompt rows too (lockstep mirroring means the draft slot
            # index IS h.slot). Drafts regenerate no logits from the
            # last prompt row, so the full bucket <= prompt_len ships —
            # a bucket-aligned prompt resumes with ZERO draft prefill.
            drows = spec_dec.migratable_draft_rows(len(h.prompt_used))
            if drows <= 0:
                continue
            dkey = tuple(int(t) for t in h.prompt_used[:drows])
            if dkey in draft_shipped:
                continue
            dentry = spec_dec.extract_draft_rows(h.slot, drows)
            frames.append(entry_frame("draft_rows", dkey, dentry))
            draft_shipped.add(dkey)
        manifest = {
            "type": "manifest", "replica": self.name,
            "unfinished": [h.request_id for h in self.server.unfinished()],
            "n_frames": len(frames),
        }
        return [(manifest, b"")] + frames

    def _migrate_out(self) -> Tuple[int, str, bytes]:
        with self._lock:
            self.draining = True  # shipping state implies no new tenants
            blob = pack_frames(self.migrate_out_frames())
        return (200, "application/octet-stream", blob)

    def _migrate_in(self, blob: bytes) -> Tuple[int, str, bytes]:
        try:
            frames = unpack_frames(blob)
        except EnvelopeError as e:
            return _error(400, "bad_frames", str(e))
        installed = skipped = draft_installed = 0
        with self._lock:
            eng = self.server.engine
            spec_dec = getattr(self.server, "spec", None)
            for meta, payload in frames:
                kind = meta.get("type")
                if kind == "manifest":
                    continue
                if kind not in ("prefix_entry", "slot_rows", "draft_rows"):
                    return _error(400, "bad_frames",
                                  f"unknown frame type {kind!r}")
                entry: Dict[str, Any] = {}
                off = 0
                for leaf in meta["leaves"]:
                    n = int(leaf["nbytes"])
                    entry[leaf["name"]] = np.frombuffer(
                        payload[off:off + n],
                        dtype=np.dtype(leaf["dtype"]),
                    ).reshape(leaf["shape"])
                    off += n
                if kind == "draft_rows":
                    # parked for SpeculativeDecoder.prime; a peer
                    # without speculation skips — degrade, never fail
                    if spec_dec is not None and spec_dec.adopt_draft_rows(
                            tuple(meta["key"]), entry):
                        draft_installed += 1
                    else:
                        skipped += 1
                elif eng.adopt_prefix_entry(meta["key"], entry):
                    installed += 1
                else:
                    skipped += 1
        return (200, "application/json", _json_body(envelope(
            "migrate_in_result", installed=installed, skipped=skipped,
            draft_installed=draft_installed)))

    # -- streaming ------------------------------------------------------
    def stream_iter(self, request_id: str,
                    max_idle_waits: int = 240,
                    wait_s: float = 0.5) -> Iterator[Dict[str, Any]]:
        """Live token stream for one request: yields ``stream_token``
        envelopes as rounds emit them, then one ``stream_end``. Ends
        with an ``error`` envelope if the request never shows up or the
        stream idles out (the step loop died)."""
        sent = 0
        idle = 0
        while True:
            with self._stream_cv:
                buf = self._streams.get(request_id)
                fresh = [] if buf is None else buf["tokens"][sent:]
                finish = None if buf is None else buf["finish"]
                if not fresh and finish is None:
                    if not self._stream_cv.wait(wait_s):
                        idle += 1
                        if idle >= max_idle_waits:
                            yield envelope(
                                "error", error="stream_idle",
                                message=f"no progress for request "
                                        f"{request_id!r}")
                            return
                    continue
            idle = 0
            for idx, tok in fresh:
                sent += 1
                yield envelope("stream_token", request_id=request_id,
                               token=tok, token_index=idx)
            if finish is not None:
                yield envelope("stream_end", request_id=request_id,
                               finish_reason=finish)
                return

    # -- dispatch -------------------------------------------------------
    def handle(self, method: str, path: str,
               body: bytes) -> Tuple[int, str, bytes]:
        try:
            if method == "POST" and path in ("/rpc/submit", "/rpc/cancel",
                                             "/rpc/drain", "/rpc/step"):
                kind = path.rsplit("/", 1)[1]
                try:
                    doc = validate_envelope(
                        json.loads(body.decode() or "{}"), kind=kind)
                except (ValueError, EnvelopeError) as e:
                    return _error(400, "bad_envelope", str(e))
                if path == "/rpc/submit":
                    return self._submit(doc)
                if path == "/rpc/cancel":
                    return self._cancel(doc)
                if path == "/rpc/drain":
                    return self._drain(doc)
                return self._step()
            if method == "POST" and path == "/rpc/migrate_in":
                return self._migrate_in(body)
            if method == "GET" and path == "/rpc/health":
                return self._health()
            if method == "GET" and path == "/rpc/migrate_out":
                return self._migrate_out()
            if method == "GET" and path == "/metrics":
                return self._metrics()
            if method == "GET" and path == "/attrib":
                return self._attrib()
            return _error(404, "not_found",
                          f"unknown endpoint {method} {path}")
        except Exception as e:  # the boundary never leaks a traceback
            if self.flight is not None:
                self.flight.dump("rpc_error", replica=self.name,
                                 path=path, error=repr(e))
            return _error(500, "internal", repr(e))


class RpcHttpServer:
    """The worker's socket face — the TelemetryServer recipe (stdlib
    ``ThreadingHTTPServer``, daemon threads, ephemeral ``port=0``) grown
    a POST surface and chunked streaming for ``/rpc/stream``."""

    def __init__(self, worker: ReplicaWorker, port: int = 0,
                 host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, status: int, ctype: str, body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                path, _, query = self.path.partition("?")
                if path == "/rpc/stream":
                    rid = parse_qs(query).get("request_id", [""])[0]
                    self.send_response(200)
                    self.send_header("Content-Type", "application/jsonl")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    try:
                        for doc in outer.worker.stream_iter(rid):
                            data = (json.dumps(doc, sort_keys=True)
                                    + "\n").encode()
                            self.wfile.write(
                                f"{len(data):x}\r\n".encode()
                                + data + b"\r\n")
                            self.wfile.flush()
                        self.wfile.write(b"0\r\n\r\n")
                    except (BrokenPipeError, ConnectionResetError):
                        pass  # client went away mid-stream
                    return
                self._reply(*outer.worker.handle("GET", path, b""))

            def do_POST(self) -> None:  # noqa: N802 — stdlib contract
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                path = self.path.partition("?")[0]
                self._reply(*outer.worker.handle("POST", path, body))

            def log_message(self, *args) -> None:  # scrapes are noise
                pass

        self.worker = worker
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="procfleet-rpc",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()


# ---------------------------------------------------------------------
# Subprocess entry point
# ---------------------------------------------------------------------

def build_worker_from_spec(spec: Dict[str, Any]) -> ReplicaWorker:
    """Construct the replica's InferenceServer from a JSON spec:
    ``{"name", "cfg": {GPTConfig.make kwargs}, "init_seed" OR
    "snapshot": <checkpoint path>, "server": {InferenceServer kwargs},
    "spill_dir", "serving_faults"}``. Weights come from the training
    snapshot when one is named (live serving), else are re-initialized
    from the seed — every replica derives the same arrays the parent
    would, without shipping them over the boundary."""
    import jax

    from mingpt_distributed_tpu.config import GPTConfig
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.serving.fleet import WallClock
    from mingpt_distributed_tpu.serving.scheduler import InferenceServer
    from mingpt_distributed_tpu.training.faults import ServingFaultInjector

    name = spec.get("name", "replica")
    cfg = GPTConfig.make(**spec["cfg"])
    if spec.get("snapshot"):
        from mingpt_distributed_tpu.training import checkpoint as ckpt_lib

        snap = ckpt_lib.restore_inference_params(spec["snapshot"], cfg)
        if snap is None:
            raise FileNotFoundError(
                f"worker {name}: no snapshot at {spec['snapshot']!r}")
        params = jax.device_put(snap.params)
    else:
        params = gpt.init(jax.random.key(int(spec.get("init_seed", 0))),
                          cfg)
    injector = (ServingFaultInjector(spec["serving_faults"])
                if spec.get("serving_faults") else None)
    hook = injector.round_hook(name) if injector is not None else None
    server_kwargs = dict(spec.get("server", {}))
    if spec.get("draft") == "self" and int(spec.get("spec_k", 0)) >= 1:
        # self-speculation: the target doubles as its own draft — the
        # cheapest way to give a subprocess worker a real draft pool
        # (full state-complete migration coverage, ~100% greedy accept)
        server_kwargs.update(draft_params=params, draft_cfg=cfg,
                             spec_k=int(spec["spec_k"]))
    server = InferenceServer(
        params, cfg, clock=WallClock().now, fault_hook=hook,
        **server_kwargs)
    flight = None
    spill = spec.get("spill_dir")
    if spill:
        from mingpt_distributed_tpu.telemetry import (
            FlightRecorder,
            render_prometheus,
        )
        os.makedirs(spill, exist_ok=True)
        flight = FlightRecorder(capacity=256, out_dir=spill,
                                registry=server.metrics.registry)
        flight.metrics_providers[name] = (
            lambda: render_prometheus(server.metrics.registry))
    pinj = None
    if spec.get("process_faults"):
        from mingpt_distributed_tpu.training.faults import (
            ProcessFaultInjector,
        )

        # no sleep injected: slow_socket is a client-side fault; the
        # worker-side verdicts that matter here are stuck_step and kill
        pinj = ProcessFaultInjector(spec["process_faults"])
    return ReplicaWorker(server, name=name, flight=flight, pinj=pinj)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m ...procfleet.worker spec.json`` — build the server,
    bind the RPC socket, print the hello envelope on stdout (the
    supervisor's handshake), then wait for SIGTERM and exit with the
    fleet's requeue code (75): the scheduler-requeue contract now
    applies per replica process."""
    import signal
    import sys

    from mingpt_distributed_tpu.serving.fleet import REQUEUE_EXIT_CODE

    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) != 1:
        # graftlint: disable-next=GL010 — CLI usage error, pre-telemetry
        print("usage: python -m mingpt_distributed_tpu.serving."
              "procfleet.worker <spec.json>", file=sys.stderr)
        return 2
    with open(args[0]) as f:
        spec = json.load(f)
    worker = build_worker_from_spec(spec)
    httpd = RpcHttpServer(worker, port=int(spec.get("port", 0)))
    if worker.flight is not None:
        worker.flight.dump("spawn", replica=worker.name, pid=os.getpid())
    # stdout IS the wire here: the supervisor blocks on this hello line
    # to learn the bound port
    # graftlint: disable-next=GL010
    print(json.dumps(envelope("hello", port=httpd.port, pid=os.getpid(),
                              name=worker.name), sort_keys=True),
          flush=True)
    stop = threading.Event()

    def _on_term(*_):
        if worker.wedged.is_set():
            # wedged inside the step RPC: a real wedge (a C loop holding
            # the GIL) never runs this handler — refuse the graceful
            # exit so the supervisor's SIGKILL rung is genuinely needed
            return
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    stop.wait()
    if worker.flight is not None:
        worker.flight.dump("drain", replica=worker.name,
                           unfinished=len(worker.server.unfinished()))
    httpd.close()
    return REQUEUE_EXIT_CODE


if __name__ == "__main__":
    raise SystemExit(main())
