"""Cross-host fleet control plane (ISSUE 19).

The process fleet (ISSUEs 16–17) supervises replica *processes* on one
machine. This module adds the layer above: a mesh of **hosts**, each
running a :class:`HostAgent` that owns its local
:class:`~.supervisor.ProcessSupervisor`/:class:`~.supervisor.ProcRouter`
and speaks to its peers over the same ``mingpt-rpc/1`` envelope grammar
— heartbeats, signed control frames, and the size-framed transfer
channel, now bandwidth-paced. A :class:`CrossHostRouter` fronts the
mesh: it routes requests to admitting hosts, collects token emissions,
and fails requests over *across* hosts when a whole machine dies or
partitions away.

Design pillars, each pinned by tests:

* **Failure detection is a ladder, not a bit.** Peers are seeded from a
  static roster; liveness comes from heartbeats on the injected clock.
  A peer degrades ``alive → suspect → quarantined → dead`` on elapsed
  silence (2.5× / 5× / 10× the heartbeat interval by default), and
  recovers only after ``recover_beats`` consecutive good beats —
  hysteresis, so one missed beat never flaps a peer and a flaky link
  can't oscillate quarantine.

* **Split-brain is prevented by epoch fencing, twice.** A host that
  loses quorum contact stops *admitting* within one heartbeat deadline
  (``submit`` sheds with ``reason="no_quorum"``). And because a
  partitioned host keeps decoding the work it already holds, the
  frontend fences its stale emissions: every token carries the
  emitting host + epoch, and tokens from a (host, attempt) that is no
  longer the request's current placement — or from an epoch below the
  request's fence — are dropped and counted, never double-emitted.
  Failing over a victim bumps the fleet epoch and pushes it to every
  quorate host, so a partitioned-then-healed host rejoins *behind* the
  fence.

* **Trust is explicit.** With a shared fleet secret, every control
  envelope is HMAC-signed over its canonical bytes with a per-sender
  monotonic nonce (:class:`~.rpc.FleetAuth`); unsigned, tampered and
  replayed frames are rejected with typed errors and distinct
  ``mingpt_fleet_auth_rejects_total{reason}`` counts. Auth is off by
  default and signed/unsigned envelopes validate identically, so the
  single-host paths stay byte-identical.

* **Bandwidth is a budget, not a hope.** Cross-host migration ships
  the same ``MGPTRPC1`` blob as local migration, but through a
  token-bucket :class:`PacedChannel`: chunks are charged against
  ``bytes_per_s`` on the injected clock (pacing never calls
  ``time.sleep`` — this module imports no ``time`` at all and is in
  graftlint GL007's clock scope), each chunk carries a sha256 digest,
  a dropped/partitioned link retries from the last acked chunk, and an
  exhausted retry budget degrades to plain re-route — requests are
  never lost, they merely re-prefill.

Network chaos (``partition`` / ``drop_frame`` / ``slow_link`` /
``host_kill``) rides
:class:`~mingpt_distributed_tpu.training.faults.NetworkFaultInjector`
under the shared FaultSpec grammar, and
:func:`build_loopback_fleet` wires a whole multi-host mesh in-process
over :class:`~.transport.LoopbackHostLink` — two identical partition
drills on :class:`~..fleet.VirtualClock` produce byte-identical
reports, no sockets involved.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from mingpt_distributed_tpu.serving.fleet import VirtualClock
from mingpt_distributed_tpu.serving.procfleet.rpc import (
    RPC_SCHEMA,
    AuthError,
    EnvelopeError,
    FleetAuth,
    TransportError,
    envelope,
    pack_frames,
    unpack_frames,
    validate_envelope,
)
from mingpt_distributed_tpu.serving.requests import Request, ShedError
from mingpt_distributed_tpu.telemetry import (
    MetricsRegistry,
    merge_fleet_pages,
    render_prometheus,
)
from mingpt_distributed_tpu.training.faults import (
    LinkPartitioned,
    NetworkFaultInjector,
)

__all__ = [
    "PacedTransferError",
    "PacedChannel",
    "HostAgent",
    "CrossHandle",
    "CrossHostRouter",
    "build_loopback_fleet",
]

#: help text shared with FleetAuth so both land in the ONE counter family
_AUTH_REJECTS_HELP = "envelopes/frames rejected by fleet auth, by reason"

_HOST_STATES = ("alive", "suspect", "quarantined", "dead")


# ---------------------------------------------------------------------
# PacedChannel — the bandwidth-budgeted transfer channel
# ---------------------------------------------------------------------

class PacedTransferError(TransportError):
    """A paced transfer exhausted its per-chunk retry budget. The blob
    did NOT arrive; the caller degrades to plain re-route (requests
    re-prefill on the destination) — degraded, never lost."""


class PacedChannel:
    """Token-bucket pacing over the size-framed transfer channel.

    The bucket starts empty and refills at ``bytes_per_s`` (burst capped
    at one chunk), so on a virtual clock a transfer of B bytes takes
    exactly ``B / bytes_per_s`` seconds plus any injected ``slow_link``
    latency — the pacing math the acceptance test pins. Waiting is
    ``clock.advance`` by default (GL007-clean; two identical runs pace
    identically); against a wall clock pass ``sleep=time.sleep`` *at the
    call site* (the serve.py drill does) and the wait becomes real.

    ``send`` is resumable: every chunk carries a sha256 digest and a
    sequence number, the receiver acks each chunk, and a partitioned
    link / dropped frame / digest NACK retries the *same* chunk — from
    the last acked frame, never from zero. Retried chunks are charged
    against the bandwidth budget again (the bytes crossed the wire
    again). ``bytes_per_s=None`` disables pacing (label
    ``paced="false"`` on the transfer counters)."""

    def __init__(self, clock, bytes_per_s: Optional[float] = None,
                 chunk_bytes: int = 65536, max_retries: int = 3,
                 burst_bytes: Optional[float] = None, registry=None,
                 sleep: Optional[Callable[[float], None]] = None):
        if chunk_bytes <= 0:
            raise ValueError("chunk_bytes must be positive")
        self.clock = clock
        self.bytes_per_s = bytes_per_s
        self.chunk_bytes = chunk_bytes
        self.max_retries = max_retries
        self.burst_bytes = float(burst_bytes if burst_bytes is not None
                                 else chunk_bytes)
        self.sleep = sleep
        self._tokens = 0.0
        self._last_refill = clock.now()
        self._xfer_bytes = None
        self._xfer_seconds = None
        if registry is not None:
            self._xfer_bytes = registry.counter(
                "mingpt_fleet_xfer_bytes_total",
                help="transfer-channel bytes shipped cross-host (includes "
                     "retried chunks — bytes that crossed the wire)",
                labels=("paced",))
            self._xfer_seconds = registry.histogram(
                "mingpt_fleet_xfer_seconds",
                help="end-to-end paced transfer durations on the fleet "
                     "clock",
                labels=("paced",))
            for paced in ("true", "false"):
                self._xfer_bytes.labels(paced=paced).inc(0)

    @property
    def _paced_label(self) -> str:
        return "true" if self.bytes_per_s is not None else "false"

    def _wait(self, dt: float) -> None:
        if dt <= 0:
            return
        if self.sleep is not None:
            self.sleep(dt)
        else:
            self.clock.advance(dt)

    def charge(self, nbytes: int, extra_s: float = 0.0) -> None:
        """Block (virtually or really) until ``nbytes`` fit the budget.
        ``extra_s`` is injected link latency — it is waited but does NOT
        refill the bucket: latency is not bandwidth."""
        if self.bytes_per_s is not None:
            now = self.clock.now()
            self._tokens = min(
                self.burst_bytes,
                self._tokens + (now - self._last_refill) * self.bytes_per_s)
            self._last_refill = now
        if extra_s > 0:
            self._wait(extra_s)
            if self.bytes_per_s is not None:
                self._last_refill = self.clock.now()
        if self.bytes_per_s is None or nbytes <= 0:
            return
        if self._tokens < nbytes:
            self._wait((nbytes - self._tokens) / self.bytes_per_s)
            self._last_refill = self.clock.now()
            self._tokens = float(nbytes)
        self._tokens -= nbytes

    def send(self, link, blob: bytes, xfer_id: str, src: str, dst: str,
             net: Optional[NetworkFaultInjector] = None,
             auth: Optional[FleetAuth] = None,
             meta_extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Ship ``blob`` to the peer behind ``link`` in paced, digested,
        individually-acked chunks. Returns a transfer report (the final
        ack rides in ``"ack"`` — for a migration it carries the install
        result). Raises :class:`PacedTransferError` when any single
        chunk exhausts ``max_retries``."""
        chunks = [blob[i:i + self.chunk_bytes]
                  for i in range(0, len(blob), self.chunk_bytes)] or [b""]
        start = self.clock.now()
        # every transfer starts with an EMPTY bucket: idle time between
        # transfers never becomes burst credit, so a paced transfer of B
        # bytes takes exactly B/bytes_per_s (+ injected latency) — the
        # deterministic budget the acceptance test pins
        self._tokens = 0.0
        self._last_refill = start
        retries = 0
        last_ack: Dict[str, Any] = {}
        seq = 0
        while seq < len(chunks):
            chunk = chunks[seq]
            for attempt in itertools.count():
                def _retry(why: str) -> None:
                    nonlocal retries
                    retries += 1
                    if attempt >= self.max_retries:
                        raise PacedTransferError(
                            f"transfer {xfer_id} chunk {seq}/{len(chunks)} "
                            f"({src}->{dst}) failed after "
                            f"{attempt + 1} attempts: {why}")
                extra_s = 0.0
                if net is not None:
                    try:
                        extra_s = net.link_verdict(src, dst)
                    except LinkPartitioned as e:
                        _retry(str(e))
                        continue
                # the chunk occupies the link whether or not it survives:
                # pace first, then roll the drop dice
                self.charge(len(chunk), extra_s)
                if self._xfer_bytes is not None:
                    self._xfer_bytes.labels(paced=self._paced_label).inc(
                        len(chunk))
                if net is not None and net.frame_verdict(src, dst):
                    _retry("frame dropped in flight")
                    continue
                meta = envelope(
                    "xfer_chunk", xfer_id=xfer_id, seq=seq,
                    n_chunks=len(chunks),
                    digest=hashlib.sha256(chunk).hexdigest(),
                    total_bytes=len(blob), **(meta_extra or {}))
                if auth is not None:
                    auth.sign(meta)
                try:
                    ack = link.post_bytes("/host/xfer_chunk",
                                          pack_frames([(meta, chunk)]))
                except (TransportError, EnvelopeError) as e:
                    _retry(repr(e))
                    continue
                if ack.get("kind") != "xfer_ack" or not ack.get("ok"):
                    _retry(f"peer NACK: {ack.get('message', ack.get('kind'))}")
                    continue
                if auth is not None:
                    try:
                        auth.verify(ack)
                    except AuthError as e:
                        _retry(f"unverifiable ack: {e}")
                        continue
                last_ack = ack
                break
            seq += 1
        elapsed = self.clock.now() - start
        if self._xfer_seconds is not None:
            self._xfer_seconds.labels(paced=self._paced_label).observe(
                elapsed)
        return {"xfer_id": xfer_id, "bytes": len(blob),
                "chunks": len(chunks), "retries": retries,
                "transfer_s": elapsed, "ack": last_ack}


# ---------------------------------------------------------------------
# HostAgent — one host's membership, auth, and serving authority
# ---------------------------------------------------------------------

class HostAgent:
    """One host in the mesh: owns the local router/supervisor, beats its
    roster peers on the injected clock, tracks their state ladder, and
    — critically — refuses to admit new work the moment it cannot see a
    quorum of the roster (the first half of split-brain prevention; the
    frontend's emission fence is the second)."""

    def __init__(self, host: str, router, roster, clock,
                 secret: Optional[str] = None, registry=None,
                 heartbeat_interval_s: float = 0.05,
                 suspect_after_s: Optional[float] = None,
                 quarantine_after_s: Optional[float] = None,
                 dead_after_s: Optional[float] = None,
                 recover_beats: int = 2,
                 quorum: Optional[int] = None):
        if host not in roster:
            raise ValueError(f"host {host!r} is not in its own roster")
        self.host = host
        self.router = router
        self.roster = sorted(roster)
        self.clock = clock
        self.registry = (registry if registry is not None
                         else router.supervisor.registry)
        self.heartbeat_interval_s = heartbeat_interval_s
        # one missed beat can never suspect a peer: the earliest rung of
        # the ladder sits past two intervals
        self.suspect_after_s = (suspect_after_s if suspect_after_s
                                is not None else 2.5 * heartbeat_interval_s)
        self.quarantine_after_s = (quarantine_after_s if quarantine_after_s
                                   is not None else 5.0 * heartbeat_interval_s)
        self.dead_after_s = (dead_after_s if dead_after_s is not None
                             else 10.0 * heartbeat_interval_s)
        self.recover_beats = recover_beats
        self.quorum = (quorum if quorum is not None
                       else len(self.roster) // 2 + 1)
        self.auth: Optional[FleetAuth] = None
        if secret:
            self.auth = FleetAuth(secret, sender=host,
                                  registry=self.registry)
        self.alive = True
        self.epoch = 0
        self._seq = 0
        self._next_beat = clock.now()
        self.links: Dict[str, Any] = {}
        #: peer -> {"last_contact", "state", "good_beats"}
        self.peers: Dict[str, Dict[str, Any]] = {}
        #: in-flight chunked transfers: xfer_id -> {"meta", "chunks"}
        self._xfers: Dict[str, Dict[str, Any]] = {}
        self._hosts_gauge = self.registry.gauge(
            "mingpt_fleet_hosts",
            help="roster hosts by membership state, from this host's "
                 "view (self counts as alive while serving)",
            labels=("state",))
        for state in _HOST_STATES:
            self._hosts_gauge.labels(state=state).set(0)
        # same family FleetAuth bumps — registered here too so the
        # reasons pre-exist on the scrape even before a first reject,
        # and so the digest NACK path can count without auth enabled
        self._rejects = self.registry.counter(
            "mingpt_fleet_auth_rejects_total",
            help=_AUTH_REJECTS_HELP, labels=("reason",))
        for reason in ("unsigned", "bad_mac", "replay", "frame_digest"):
            self._rejects.labels(reason=reason).inc(0)

    # -- membership -------------------------------------------------------
    def connect(self, links: Dict[str, Any]) -> None:
        """Wire peer links (host -> link object). Every roster peer is
        seeded ``alive`` as of now — the ladder needs silence to
        degrade, not evidence to trust."""
        self.links = dict(links)
        now = self.clock.now()
        for peer in self.roster:
            if peer == self.host:
                continue
            self.peers[peer] = {"last_contact": now, "state": "alive",
                                "good_beats": 0}

    def record_contact(self, peer: str) -> None:
        st = self.peers.get(peer)
        if st is None:
            return  # not in the roster: membership is static, ignore
        st["last_contact"] = self.clock.now()
        st["good_beats"] += 1

    def beat(self) -> None:
        """Send one heartbeat round when the interval has elapsed. A
        peer that can't be reached (partition, dead host, bad auth on
        the ack) simply misses contact — the ladder, not this method,
        decides what that means."""
        now = self.clock.now()
        if now < self._next_beat:
            return
        self._next_beat = now + self.heartbeat_interval_s
        for peer in sorted(self.links):
            self._seq += 1
            doc = envelope("heartbeat", host=self.host, epoch=self.epoch,
                           seq=self._seq)
            if self.auth is not None:
                self.auth.sign(doc)
            try:
                ack = self.links[peer].call("/host/heartbeat", doc)
            except (TransportError, EnvelopeError):
                continue  # missed beat
            if ack.get("kind") != "heartbeat_ack":
                continue  # peer rejected us (auth / drift): no contact
            if self.auth is not None:
                try:
                    self.auth.verify(ack)
                except AuthError:
                    continue
            self.epoch = max(self.epoch, ack["epoch"])
            self.record_contact(peer)

    def refresh_peer_states(self) -> None:
        """Advance the ladder from elapsed silence. Recovery out of
        quarantined/dead requires ``recover_beats`` consecutive good
        beats (hysteresis); suspect recovers immediately — it is the
        'one more missed beat and I worry' rung, not a verdict."""
        now = self.clock.now()
        for peer in sorted(self.peers):
            st = self.peers[peer]
            elapsed = now - st["last_contact"]
            if elapsed >= self.dead_after_s:
                cand = "dead"
            elif elapsed >= self.quarantine_after_s:
                cand = "quarantined"
            elif elapsed >= self.suspect_after_s:
                cand = "suspect"
            else:
                cand = "alive"
            if cand != "alive":
                st["good_beats"] = 0
            elif (st["state"] in ("quarantined", "dead")
                    and st["good_beats"] < self.recover_beats):
                cand = st["state"]  # hold the verdict until proven
            st["state"] = cand

    def has_quorum(self) -> bool:
        """Can this host see a majority of the roster (itself
        included)? Quorum is over *alive* peers only — a suspect peer
        already doesn't count, which is what makes 'stop admitting
        within one heartbeat deadline' hold."""
        seen = 1 + sum(1 for st in self.peers.values()
                       if st["state"] == "alive")
        return seen >= self.quorum

    @property
    def admitting(self) -> bool:
        return self.alive and self.has_quorum()

    # -- serving ----------------------------------------------------------
    def submit(self, request: Request):
        if not self.admitting:
            raise ShedError(
                f"host {self.host} cannot see a quorum of "
                f"{self.roster} — refusing to admit (split-brain guard)",
                reason="no_quorum")
        return self.router.submit(request)

    def kill_host(self) -> None:
        """The whole machine dies: every local replica SIGKILLed, the
        agent stops beating and answering. Used by ``host_kill`` chaos
        and the serve.py drill."""
        self.alive = False
        for rep in self.router.supervisor.replicas:
            if rep.state != "drained" and rep.backend is not None:
                try:
                    rep.backend.sigkill()
                except OSError:
                    pass

    def step(self) -> bool:
        """One host round: beat → ladder → gauges → local router round.
        A dead host does nothing (its peers' ladders do the talking)."""
        if not self.alive:
            return False
        self.beat()
        self.refresh_peer_states()
        counts = {state: 0 for state in _HOST_STATES}
        counts["alive"] = 1  # self
        for st in self.peers.values():
            counts[st["state"]] += 1
        for state, n in counts.items():
            self._hosts_gauge.labels(state=state).set(n)
        return self.router.step()

    # -- the host RPC surface ---------------------------------------------
    def handle_host(self, path: str, body: bytes) -> bytes:
        """Serve one peer call. Auth/validation failures answer with an
        ``error`` envelope (the counter was already bumped by
        FleetAuth) — byte-faithful to what a socket server would
        return, so loopback drills exercise the reject path exactly."""
        try:
            if path == "/host/heartbeat":
                return self._handle_heartbeat(body)
            if path == "/host/xfer_chunk":
                return self._handle_xfer_chunk(body)
            return self._error_bytes("not_found",
                                     f"unknown host path {path!r}")
        except (AuthError, EnvelopeError) as e:
            return self._error_bytes(type(e).__name__, str(e))

    @staticmethod
    def _to_bytes(doc: Dict[str, Any]) -> bytes:
        return json.dumps(doc, sort_keys=True).encode()

    def _error_bytes(self, error: str, message: str) -> bytes:
        return self._to_bytes({"schema": RPC_SCHEMA, "kind": "error",
                               "error": error, "message": message})

    def _handle_heartbeat(self, body: bytes) -> bytes:
        doc = validate_envelope(json.loads(body.decode()),
                                kind="heartbeat")
        if self.auth is not None:
            self.auth.verify(doc)
        self.record_contact(doc["host"])
        self.epoch = max(self.epoch, doc["epoch"])
        ack = envelope("heartbeat_ack", host=self.host, epoch=self.epoch,
                       seq=doc["seq"])
        if self.auth is not None:
            self.auth.sign(ack)
        return self._to_bytes(ack)

    def _handle_xfer_chunk(self, body: bytes) -> bytes:
        frames = unpack_frames(body)
        if len(frames) != 1:
            raise EnvelopeError(
                f"xfer_chunk carries exactly one frame, got {len(frames)}")
        meta, chunk = frames[0]
        validate_envelope(meta, kind="xfer_chunk")
        if self.auth is not None:
            self.auth.verify(meta)
        xfer_id, seq = meta["xfer_id"], meta["seq"]
        if hashlib.sha256(chunk).hexdigest() != meta["digest"]:
            # corrupted in flight: NACK so the sender retries this chunk;
            # counted under the auth-rejects family (reason=frame_digest)
            self._rejects.labels(reason="frame_digest").inc()
            return self._ack_bytes(xfer_id, seq, ok=False,
                                   message="frame digest mismatch")
        st = self._xfers.setdefault(xfer_id, {"meta": meta, "chunks": {}})
        st["chunks"][seq] = chunk
        extra: Dict[str, Any] = {"complete": False}
        if len(st["chunks"]) == meta["n_chunks"]:
            blob = b"".join(st["chunks"][i]
                            for i in range(meta["n_chunks"]))
            del self._xfers[xfer_id]
            extra["complete"] = True
            if meta.get("purpose") == "migrate":
                extra.update(self._install_migration(meta, blob))
        return self._ack_bytes(xfer_id, seq, ok=True, **extra)

    def _ack_bytes(self, xfer_id: str, seq: int, ok: bool,
                   **extra: Any) -> bytes:
        ack = envelope("xfer_ack", xfer_id=xfer_id, seq=seq, ok=ok,
                       **extra)
        if self.auth is not None:
            self.auth.sign(ack)
        return self._to_bytes(ack)

    def _install_migration(self, meta: Dict[str, Any],
                           blob: bytes) -> Dict[str, Any]:
        """A fully reassembled migration blob: install into the named
        (or least-loaded ready) local replica. An install failure is
        reported in the ack, NOT as a transport failure — the transfer
        itself succeeded, retrying chunks would not help."""
        sup = self.router.supervisor
        dst = None
        if meta.get("dst_replica"):
            dst = sup.replica_by_name(meta["dst_replica"])
        else:
            cands = [r for r in sup.ready_replicas()
                     if not getattr(r, "draining", False)]
            dst = min(cands, key=lambda r: (r.load, r.index), default=None)
        if dst is None or dst.state != "ready":
            return {"install_error": "no ready replica to install into",
                    "installed": 0, "skipped": 0, "draft_installed": 0}
        try:
            resp = self.router.install_migrate_blob(dst, blob)
        except (TransportError, EnvelopeError) as e:
            return {"install_error": repr(e), "installed": 0,
                    "skipped": 0, "draft_installed": 0}
        return {"installed": resp["installed"],
                "skipped": resp["skipped"],
                "draft_installed": resp.get("draft_installed", 0),
                "to_replica": dst.name}


# ---------------------------------------------------------------------
# CrossHostRouter — the fleet frontend over the mesh
# ---------------------------------------------------------------------

@dataclass
class CrossHandle:
    """Host-independent view of one request routed through the mesh.
    ``tokens`` is the caller-visible stream: append-only, deduped
    across retries AND fenced against stale hosts."""

    request: Request
    request_id: str
    submit_time: float = 0.0
    tokens: List[int] = field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    current_host: Optional[str] = None
    #: (host, local fleet request_id) of the CURRENT attempt — tokens
    #: from any other key are stale by definition
    local_key: Optional[Tuple[str, str]] = None
    fence_epoch: int = 0
    attempts: int = 1                     # host placements so far
    hosts: List[str] = field(default_factory=list)
    duplicates_suppressed: int = 0
    fenced: int = 0                       # stale-host emissions dropped
    fault_at: Optional[float] = None
    recovery_s: Optional[float] = None
    failed_from: Optional[str] = None
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None


class CrossHostRouter:
    """Routes requests over the :class:`HostAgent` mesh and owns the
    second half of split-brain prevention: the emission fence.

    Token emissions are *collected*, not streamed through: each local
    router's ``on_token`` hook appends ``(host, epoch-at-emit,
    local_request_id, index, token)`` and :meth:`step` replays them in
    deterministic order — dropping (and counting) any emission whose
    (host, attempt) is no longer the request's current placement or
    whose epoch sits below the request's fence. A partitioned host can
    decode all it wants; its tokens cannot reach the caller twice.

    Cross-host failover: when every quorate peer's ladder holds a host
    at ``quarantined``/``dead``, the host is declared failed — the
    fleet epoch bumps, pushes to the quorate hosts, and every unfinished
    request placed there re-submits on the least-loaded admitting host
    with ``recovery_log`` path ``crosshost`` stamped on its first
    post-fault token."""

    def __init__(self, agents: Dict[str, "HostAgent"], clock,
                 net: Optional[NetworkFaultInjector] = None,
                 on_token: Optional[Callable[[CrossHandle, int],
                                             None]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 max_failovers: int = 2,
                 paced: Optional[PacedChannel] = None):
        self.agents = dict(agents)
        self.clock = clock
        self.net = net
        self.on_token = on_token
        self.registry = registry if registry is not None else MetricsRegistry()
        self.max_failovers = max_failovers
        self.paced = (paced if paced is not None
                      else PacedChannel(clock, registry=self.registry))
        self.fleet_epoch = 0
        self.handles: Dict[str, CrossHandle] = {}
        #: (host, local request_id) -> (CrossHandle, local FleetHandle)
        self._local: Dict[Tuple[str, str], Tuple[CrossHandle, Any]] = {}
        self._emissions: List[Tuple[str, int, str, int, int]] = []
        self._pending: List[CrossHandle] = []
        self._declared_failed: set = set()
        self._ids = itertools.count()
        self._xfer_ids = itertools.count()
        self._fenced = self.registry.counter(
            "mingpt_fleet_fenced_emissions_total",
            help="stale-host token emissions dropped at the frontend "
                 "fence (the cross-host zero-double-emit invariant)",
            labels=("host",))
        self._failovers = self.registry.counter(
            "mingpt_fleet_crosshost_failovers_total",
            help="requests re-placed on a surviving host after their "
                 "host was declared failed by the quorate ladder view",
            labels=("from_host",))
        self._requests = self.registry.counter(
            "mingpt_fleet_cross_requests_total",
            help="cross-host routed requests by terminal outcome",
            labels=("outcome",))
        for outcome in ("completed", "deadline", "error"):
            self._requests.labels(outcome=outcome).inc(0)
        for host in sorted(self.agents):
            self._fenced.labels(host=host).inc(0)
            self._failovers.labels(from_host=host).inc(0)
            self.agents[host].router.on_token = self._make_collector(host)

    def _make_collector(self, host: str):
        agent = self.agents[host]

        def collect(fh, token: int) -> None:
            # epoch is captured AT EMIT TIME: tokens computed behind a
            # partition carry the stale epoch even if processed after
            self._emissions.append(
                (host, agent.epoch, fh.request_id, len(fh.tokens) - 1,
                 token))
        return collect

    # -- admission --------------------------------------------------------
    def _admitting_agents(self, prefer: Optional[str] = None,
                          avoid: Optional[str] = None) -> List["HostAgent"]:
        cands = [a for a in self.agents.values()
                 if a.admitting and a.host != avoid]
        cands.sort(key=lambda a: (a.host != prefer,
                                  a.router.fleet_queue_depth()
                                  + len(a.router._attempts), a.host))
        return cands

    def submit(self, request: Request) -> CrossHandle:
        """Route one request to the least-loaded admitting host. Raises
        :class:`ShedError` (``reason="no_quorum"``) when no host can
        see a quorum — the fleet would rather refuse work than serve it
        from both sides of a partition."""
        last_shed: Optional[ShedError] = None
        for agent in self._admitting_agents():
            try:
                fh = agent.submit(request)
            except ShedError as e:
                last_shed = e
                continue
            cross = CrossHandle(
                request=request,
                request_id=f"cross-{next(self._ids)}",
                submit_time=self.clock.now(),
                current_host=agent.host,
                local_key=(agent.host, fh.request_id))
            cross.hosts.append(agent.host)
            self.handles[cross.request_id] = cross
            self._local[cross.local_key] = (cross, fh)
            return cross
        if last_shed is not None:
            raise last_shed
        raise ShedError(
            "no host can see a quorum — refusing to admit into a "
            "partitioned fleet", reason="no_quorum")

    def _resubmit(self, cross: CrossHandle, prefer: Optional[str] = None,
                  avoid: Optional[str] = None) -> bool:
        """Place an existing request on a (new) admitting host. The
        current placement changes, which fences every emission from the
        old one. Parks in the retry queue when nowhere admits."""
        for agent in self._admitting_agents(prefer=prefer, avoid=avoid):
            try:
                fh = agent.submit(cross.request)
            except ShedError:
                continue
            cross.attempts += 1
            cross.current_host = agent.host
            cross.local_key = (agent.host, fh.request_id)
            cross.hosts.append(agent.host)
            self._local[cross.local_key] = (cross, fh)
            return True
        if cross not in self._pending:
            self._pending.append(cross)
        return False

    # -- the cross-host round ---------------------------------------------
    def step(self) -> bool:
        """One mesh round: host_kill verdicts → every live agent's host
        round (sorted order — deterministic) → fence + dedup the
        collected emissions → reconcile finished local attempts →
        declare/fail-over dead hosts → retry parked requests. Returns
        True while any cross-host request is unfinished."""
        if self.net is not None:
            for host in sorted(self.agents):
                agent = self.agents[host]
                if agent.alive and self.net.host_verdict(host):
                    agent.kill_host()
        for host in sorted(self.agents):
            self.agents[host].step()
        self._process_emissions()
        self._reconcile_local()
        self._detect_failed_hosts()
        if self._pending:
            parked, self._pending = self._pending, []
            for cross in parked:
                if not cross.finished:
                    self._resubmit(cross, avoid=cross.failed_from)
        return any(not c.finished for c in self.handles.values())

    def run_until_drained(self, max_steps: Optional[int] = None) -> None:
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                unfinished = [cid for cid, c in self.handles.items()
                              if not c.finished]
                raise RuntimeError(
                    f"cross-host fleet not drained after {max_steps} "
                    f"steps (unfinished={unfinished})")

    def _process_emissions(self) -> None:
        emissions, self._emissions = self._emissions, []
        for host, epoch, local_id, idx, token in emissions:
            entry = self._local.get((host, local_id))
            if entry is None:
                continue  # not cross-managed (or already reconciled away)
            cross, _fh = entry
            if cross.finished:
                continue
            if ((host, local_id) != cross.local_key
                    or epoch < cross.fence_epoch):
                # THE fence: a stale placement (failed-over request) or
                # a stale epoch (partitioned-then-healed host) can never
                # reach the caller — counted, never delivered
                cross.fenced += 1
                self._fenced.labels(host=host).inc()
                continue
            if idx < len(cross.tokens):
                # a re-routed attempt re-deriving tokens the caller
                # already saw — deterministic decode makes them equal
                cross.duplicates_suppressed += 1
                continue
            if idx > len(cross.tokens):
                raise RuntimeError(
                    f"{cross.request_id}: stream gap — emission index "
                    f"{idx} with {len(cross.tokens)} tokens delivered")
            cross.tokens.append(token)
            now = self.clock.now()
            if cross.first_token_time is None:
                cross.first_token_time = now
            cross.last_token_time = now
            if cross.fault_at is not None:
                # first NEW caller-visible token since the host fault:
                # the cross-host recovery tail, logged on the ADOPTING
                # host's supervisor under path="crosshost"
                rec = now - cross.fault_at
                cross.recovery_s = rec
                cross.fault_at = None
                sup = self.agents[host].router.supervisor
                info = {"replica": cross.failed_from, "path": "crosshost",
                        "recovery_s": rec, "adopted": host}
                sup.recovery_log.append(info)
                sup._recovery.labels(path="crosshost").observe(rec)
            if self.on_token is not None:
                self.on_token(cross, token)

    def _finalize(self, cross: CrossHandle, reason: str) -> None:
        cross.finished = True
        cross.finish_reason = reason
        outcome = "completed" if reason in ("length", "eos") else reason
        self._requests.labels(outcome=outcome).inc()

    def _reconcile_local(self) -> None:
        for key in list(self._local.keys()):
            cross, fh = self._local[key]
            if not fh.finished:
                continue
            del self._local[key]
            if key != cross.local_key or cross.finished:
                continue  # a stale attempt concluded: nothing to adopt
            if fh.finish_reason in ("length", "eos"):
                self._finalize(cross, fh.finish_reason)
            elif fh.finish_reason == "deadline":
                self._finalize(cross, "deadline")
            else:  # local error, retries exhausted on that host
                cross.error = repr(fh.error) if fh.error else "error"
                if cross.attempts > self.max_failovers:
                    self._finalize(cross, "error")
                else:
                    self._resubmit(cross)

    def _detect_failed_hosts(self) -> None:
        """Declare a host failed when every *quorate* peer's ladder
        holds it at quarantined/dead — one suspicious peer is a flaky
        link, unanimity among hosts that can see a majority is a
        verdict. Healing (all quorate views back to alive, which the
        per-agent hysteresis already gates) lifts the declaration."""
        now = self.clock.now()
        quorate = {h: a for h, a in sorted(self.agents.items())
                   if a.alive and a.has_quorum()}
        for host in sorted(self.agents):
            views = [qa.peers[host]["state"]
                     for qh, qa in quorate.items()
                     if qh != host and host in qa.peers]
            if not views:
                continue
            if host in self._declared_failed:
                if all(v == "alive" for v in views):
                    self._declared_failed.discard(host)
                continue
            if not all(v in ("quarantined", "dead") for v in views):
                continue
            self._declared_failed.add(host)
            # epoch fence: everything the failed host computes from here
            # on is behind this number
            self.fleet_epoch = max(
                [self.fleet_epoch] + [a.epoch for a in quorate.values()]
            ) + 1
            for agent in quorate.values():
                agent.epoch = max(agent.epoch, self.fleet_epoch)
            for cross in self.handles.values():
                if cross.finished or cross.current_host != host:
                    continue
                if cross.fault_at is None:
                    cross.fault_at = now
                cross.fence_epoch = self.fleet_epoch
                cross.failed_from = host
                self._failovers.labels(from_host=host).inc()
                self._resubmit(cross, avoid=host)

    # -- cross-host migration ---------------------------------------------
    def migrate_crosshost(self, src_host: str, dst_host: str,
                          replica: Optional[str] = None,
                          dst_replica: Optional[str] = None,
                          ) -> Dict[str, Any]:
        """Live-migrate one replica's KV/prefix/draft state from
        ``src_host`` to ``dst_host`` through the paced channel, re-route
        its in-flight requests to the destination host, and retire the
        source replica. A failed transfer (exhausted chunk retries, or
        an install error on the far side) degrades to plain re-route —
        ``outcome="failed"`` on the migration counter, zero requests
        lost. Returns a ``mingpt-migrate-crosshost/1`` report."""
        if src_host == dst_host:
            raise ValueError("cross-host migration needs two hosts; use "
                             "migrate_and_drain for a local move")
        src_agent = self.agents[src_host]
        dst_agent = self.agents[dst_host]
        if not dst_agent.alive:
            raise ValueError(f"destination host {dst_host!r} is down")
        router = src_agent.router
        sup = router.supervisor
        if replica is not None:
            src_rep = sup.replica_by_name(replica)
        else:
            cands = [r for r in sup.ready_replicas()
                     if not getattr(r, "draining", False)]
            src_rep = max(cands, key=lambda r: (r.load, -r.index),
                          default=None)
        if src_rep is None or src_rep.state != "ready":
            raise ValueError(
                f"no ready replica to migrate off {src_host!r}")
        src_rep.draining = True
        blob = router.export_migrate_blob(src_rep)
        xfer_id = f"xfer-{src_host}-{next(self._xfer_ids)}"
        meta_extra: Dict[str, Any] = {"purpose": "migrate"}
        if dst_replica is not None:
            meta_extra["dst_replica"] = dst_replica
        outcome, error = "ok", None
        xfer: Dict[str, Any] = {"bytes": len(blob), "chunks": 0,
                                "retries": 0, "transfer_s": 0.0,
                                "ack": {}}
        try:
            xfer = self.paced.send(
                src_agent.links[dst_host], blob, xfer_id, src_host,
                dst_host, net=self.net, auth=src_agent.auth,
                meta_extra=meta_extra)
        except (PacedTransferError, TransportError, EnvelopeError) as e:
            outcome, error = "failed", repr(e)
        ack = xfer.get("ack") or {}
        if outcome == "ok" and ack.get("install_error"):
            outcome, error = "failed", ack["install_error"]
        sup._migrations.labels(outcome=outcome).inc()
        # re-route the source replica's in-flight requests onto the
        # DESTINATION host: the shipped prefix/KV state lives there now,
        # so the re-derive is a warm hit when the transfer landed
        moved: List[str] = []
        now = self.clock.now()
        for fh in router.detach_unfinished(src_rep.name,
                                           to_label=dst_host):
            entry = self._local.pop((src_host, fh.request_id), None)
            if entry is None:
                # not cross-managed (submitted straight at the local
                # router): re-queue locally, same as migrate_and_drain
                router._pending.append((fh, now))
                continue
            cross, _ = entry
            self._resubmit(cross, prefer=dst_host)
            moved.append(cross.request_id)
        info = router.drain_and_retire(src_rep)
        return {
            "schema": "mingpt-migrate-crosshost/1",
            "from_host": src_host,
            "to_host": dst_host,
            "from": src_rep.name,
            "to": ack.get("to_replica"),
            "outcome": outcome,
            "error": error,
            "bytes": xfer["bytes"],
            "chunks": xfer["chunks"],
            "retries": xfer["retries"],
            "transfer_s": xfer["transfer_s"],
            "entries_installed": ack.get("installed", 0),
            "entries_skipped": ack.get("skipped", 0),
            "draft_rows_installed": ack.get("draft_installed", 0),
            "requests_moved": sorted(moved),
            "src_exit_code": info.get("exit_code"),
        }

    # -- observability ----------------------------------------------------
    def fleet_metrics_page(self) -> str:
        """The whole mesh on one strict-parsed page: the frontend's own
        registry as-is, plus every live host's merged fleet page
        re-labelled under ``host=<name>`` (per-replica labels inside
        each host page survive — inner labels win on merge)."""
        pages: Dict[str, str] = {}
        for host in sorted(self.agents):
            agent = self.agents[host]
            if not agent.alive:
                continue
            pages[host] = agent.router.fleet_metrics_page()
        return merge_fleet_pages(render_prometheus(self.registry), pages,
                                 label="host")

    def summary(self) -> Dict[str, Any]:
        """Deterministic drill report — the byte-identity surface of the
        two-run partition drills (JSON-dump it sorted)."""
        return {
            "fleet_epoch": self.fleet_epoch,
            "declared_failed": sorted(self._declared_failed),
            "pending": len(self._pending),
            "hosts": {
                host: {
                    "alive": agent.alive,
                    "epoch": agent.epoch,
                    "admitting": agent.admitting,
                    "peers": {p: st["state"]
                              for p, st in sorted(agent.peers.items())},
                }
                for host, agent in sorted(self.agents.items())
            },
            "requests": {
                cid: {
                    "finish_reason": c.finish_reason,
                    "n_tokens": len(c.tokens),
                    "hosts": list(c.hosts),
                    "attempts": c.attempts,
                    "duplicates_suppressed": c.duplicates_suppressed,
                    "fenced": c.fenced,
                    "recovered": c.recovery_s is not None,
                }
                for cid, c in sorted(self.handles.items())
            },
        }


# ---------------------------------------------------------------------
# Loopback mesh builder — multi-host drills without sockets
# ---------------------------------------------------------------------

def build_loopback_fleet(params, cfg, n_hosts: int = 2,
                         n_replicas: int = 2, clock=None,
                         secret: Optional[str] = None,
                         net_faults: Optional[str] = None,
                         heartbeat_interval_s: float = 0.05,
                         quorum: Optional[int] = None,
                         on_token=None,
                         paced_bytes_per_s: Optional[float] = None,
                         max_failovers: int = 2,
                         server_kwargs: Optional[Dict[str, Any]] = None,
                         supervisor_kwargs: Optional[Dict[str, Any]] = None,
                         router_kwargs: Optional[Dict[str, Any]] = None,
                         agent_kwargs: Optional[Dict[str, Any]] = None,
                         ) -> Tuple[CrossHostRouter,
                                    Dict[str, HostAgent],
                                    NetworkFaultInjector]:
    """Wire an entire multi-host mesh in one process: per host a fresh
    registry + ProcessSupervisor (loopback backends) + ProcRouter +
    HostAgent, full-mesh :class:`~.transport.LoopbackHostLink` wiring
    through one shared :class:`NetworkFaultInjector`, and a
    :class:`CrossHostRouter` frontend — all on one shared clock, so a
    drill replayed with the same faults is byte-identical. Returns
    ``(frontend, agents, net)``."""
    from mingpt_distributed_tpu.serving.procfleet.supervisor import (
        ProcessSupervisor,
        ProcRouter,
        loopback_backend_factory,
    )
    from mingpt_distributed_tpu.serving.procfleet.transport import (
        LoopbackHostLink,
    )

    if clock is None:
        clock = VirtualClock(tick_s=0.001)
    net = NetworkFaultInjector(net_faults if net_faults is not None
                               else "", clock=clock)
    roster = [f"host{i}" for i in range(n_hosts)]
    agents: Dict[str, HostAgent] = {}
    for host in roster:
        sup = ProcessSupervisor(
            loopback_backend_factory(params, cfg,
                                     **(server_kwargs or {})),
            n_replicas=n_replicas, clock=clock,
            registry=MetricsRegistry(),
            **(supervisor_kwargs or {}))
        router = ProcRouter(sup, **(router_kwargs or {}))
        agents[host] = HostAgent(
            host, router, roster, clock, secret=secret,
            heartbeat_interval_s=heartbeat_interval_s, quorum=quorum,
            **(agent_kwargs or {}))
    for src in roster:
        agents[src].connect({
            dst: LoopbackHostLink(src, dst, agents[dst], net=net)
            for dst in roster if dst != src})
    frontend = CrossHostRouter(
        agents, clock, net=net, on_token=on_token,
        max_failovers=max_failovers)
    frontend.paced = PacedChannel(clock, bytes_per_s=paced_bytes_per_s,
                                  registry=frontend.registry)
    return frontend, agents, net
