"""``mingpt-rpc/1`` — the versioned envelope grammar of the procfleet
socket boundary (ISSUE 16).

Every JSON document that crosses the replica boundary — request or
response, loopback or real HTTP — is an *envelope*: ``{"schema":
"mingpt-rpc/1", "kind": <kind>, ...}`` with a per-kind required-field
table enforced by :func:`validate_envelope`, the same strict-validator
discipline as ``mingpt-trace/1`` / ``mingpt-flight/1`` /
``mingpt-attrib/1``. Both transport implementations validate every
envelope in BOTH directions, so a drifting worker fails loudly at the
boundary instead of corrupting router state, and the tamper battery in
tests/test_procfleet.py pins each field.

Binary state (migrated KV rows and prefix-store entries) does not ride
in JSON: it moves through the **size-framed transfer channel** —
``pack_frames``/``unpack_frames`` below. A blob is ``MAGIC`` + frame
count, then per frame a length-prefixed JSON meta header and a
length-prefixed raw payload. Length prefixes are u64 big-endian;
truncation, trailing garbage and magic drift all raise. The framing is
deliberately dumb: byte-deterministic for identical inputs (sorted-key
meta JSON), so the loopback chaos suite can assert two runs migrate
byte-identical state.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

RPC_SCHEMA = "mingpt-rpc/1"

#: magic + version tag opening every transfer-channel blob
FRAME_MAGIC = b"MGPTRPC1"

__all__ = [
    "RPC_SCHEMA",
    "FRAME_MAGIC",
    "EnvelopeError",
    "TransportError",
    "TransportTimeout",
    "TransportUnavailable",
    "AuthError",
    "UnsignedEnvelope",
    "BadSignature",
    "ReplayedNonce",
    "FleetAuth",
    "canonical_bytes",
    "envelope",
    "validate_envelope",
    "pack_frames",
    "unpack_frames",
    "request_to_wire",
    "request_from_wire",
]


class EnvelopeError(ValueError):
    """An envelope failed schema validation — protocol drift, not load."""


class TransportError(RuntimeError):
    """The socket (or loopback channel) failed mid-RPC: connection
    refused/reset, short read, dead subprocess. The replica may be dead —
    the supervisor decides by looking at the process."""


class TransportTimeout(TransportError):
    """The RPC timed out (socket timeout / injected hang). The replica
    is presumed alive; the round is lost, the breaker records a
    failure."""


class TransportUnavailable(TransportError):
    """The peer could not be reached at all (connection refused/reset
    through the bounded retry budget). Distinct from
    :class:`TransportTimeout`: nothing was in flight, so the call is
    safe to re-route rather than treat as a lost round."""


class AuthError(EnvelopeError):
    """An envelope failed fleet authentication. Subclasses carry a
    ``reason`` label matching ``mingpt_fleet_auth_rejects_total``."""

    reason = "auth"


class UnsignedEnvelope(AuthError):
    """Auth is required but the envelope carries no ``auth`` field."""

    reason = "unsigned"


class BadSignature(AuthError):
    """The HMAC over the canonical bytes does not verify — tampering or
    a wrong fleet secret."""

    reason = "bad_mac"


class ReplayedNonce(AuthError):
    """A verified envelope arrived with a non-monotonic nonce — a
    replayed (or badly reordered) frame."""

    reason = "replay"


# ---------------------------------------------------------------------
# Envelope grammar
# ---------------------------------------------------------------------

#: kind -> {field: type-or-tuple-of-types}; every field is required.
#: Optional payload rides beyond these (validated values, open fields —
#: the same posture as the trace schema: pin the contract, let
#: attributes grow).
_KIND_FIELDS: Dict[str, Dict[str, Any]] = {
    # client -> worker
    "submit": {"request": dict},
    "step": {},
    "cancel": {"request_id": str},
    "drain": {"migrate": bool},
    # worker -> client
    "hello": {"port": int, "pid": int, "name": str},
    "submit_result": {"request_id": str, "queue_depth": int},
    "step_result": {"events": list, "queue_depth": int, "occupied": int,
                    "recompiles": int, "busy": bool},
    "cancel_result": {"cancelled": bool},
    "drain_result": {"draining": bool, "unfinished": int},
    "health": {"queue_depth": int, "occupied": int, "draining": bool,
               "recompiles": int, "pid": int},
    "migrate_in_result": {"installed": int, "skipped": int,
                          "draft_installed": int},
    "stream_token": {"request_id": str, "token": int, "token_index": int},
    "stream_end": {"request_id": str, "finish_reason": str},
    "error": {"error": str, "message": str},
    # host <-> host (ISSUE 19 hostplane)
    "heartbeat": {"host": str, "epoch": int, "seq": int},
    "heartbeat_ack": {"host": str, "epoch": int, "seq": int},
    "xfer_chunk": {"xfer_id": str, "seq": int, "n_chunks": int,
                   "digest": str, "total_bytes": int},
    "xfer_ack": {"xfer_id": str, "seq": int, "ok": bool},
}

#: event types allowed inside step_result.events
_EVENT_FIELDS: Dict[str, Dict[str, Any]] = {
    "emit": {"request_id": str, "token": int, "token_index": int},
    "finish": {"request_id": str, "finish_reason": str, "n_tokens": int},
}


def envelope(kind: str, **fields: Any) -> Dict[str, Any]:
    """Mint a validated ``mingpt-rpc/1`` envelope."""
    doc = {"schema": RPC_SCHEMA, "kind": kind, **fields}
    validate_envelope(doc, kind=kind)
    return doc


def _check_fields(where: str, doc: Dict[str, Any],
                  table: Dict[str, Any]) -> None:
    for fname, ftype in table.items():
        if fname not in doc:
            raise EnvelopeError(f"{where}: missing field {fname!r}")
        if not isinstance(doc[fname], ftype):
            raise EnvelopeError(
                f"{where}: field {fname!r} must be "
                f"{getattr(ftype, '__name__', ftype)}, "
                f"got {type(doc[fname]).__name__}")
        if ftype is int and isinstance(doc[fname], bool):
            raise EnvelopeError(
                f"{where}: field {fname!r} must be int, got bool")


def validate_envelope(doc: Any, kind: Optional[str] = None) -> Dict[str, Any]:
    """Strict structural check; returns ``doc`` for chaining. ``kind``
    pins the expected kind (a submit_result answering a cancel is
    protocol drift even if well-formed)."""
    if not isinstance(doc, dict):
        raise EnvelopeError(f"envelope must be a JSON object, got "
                            f"{type(doc).__name__}")
    if doc.get("schema") != RPC_SCHEMA:
        raise EnvelopeError(
            f"schema must be {RPC_SCHEMA!r}, got {doc.get('schema')!r}")
    k = doc.get("kind")
    if k not in _KIND_FIELDS:
        raise EnvelopeError(f"unknown envelope kind {k!r}")
    if kind is not None and k != kind:
        raise EnvelopeError(f"expected kind {kind!r}, got {k!r}")
    _check_fields(f"envelope {k}", doc, _KIND_FIELDS[k])
    if k == "step_result":
        for i, ev in enumerate(doc["events"]):
            if not isinstance(ev, dict):
                raise EnvelopeError(f"step_result.events[{i}] must be an "
                                    f"object")
            et = ev.get("type")
            if et not in _EVENT_FIELDS:
                raise EnvelopeError(
                    f"step_result.events[{i}]: unknown event type {et!r}")
            _check_fields(f"event {et}", ev, _EVENT_FIELDS[et])
    if k == "submit":
        _check_fields("submit.request", doc["request"], {"prompt": list})
    return doc


# ---------------------------------------------------------------------
# Request wire form
# ---------------------------------------------------------------------

#: Request fields that cross the boundary. The trace context is carried
#: as ids+baggage (propagation), never as a live object.
_REQUEST_FIELDS = ("prompt", "max_new_tokens", "temperature", "top_k",
                   "top_p", "do_sample", "eos_id", "seed", "deadline_s",
                   "request_id", "tenant")


def request_to_wire(request) -> Dict[str, Any]:
    """Serialize a ``Request`` for the submit envelope. The trace
    context rides as ``{"trace_id", "span_id", "baggage"}`` so a
    migrated request's timeline can span processes."""
    doc = {f: getattr(request, f) for f in _REQUEST_FIELDS}
    doc["prompt"] = [int(t) for t in doc["prompt"]]
    ctx = getattr(request, "trace", None)
    if ctx is not None:
        doc["trace"] = {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
                        "baggage": dict(ctx.baggage)}
    return doc


def request_from_wire(doc: Dict[str, Any]):
    """Rebuild a ``Request`` worker-side. The propagated trace context is
    intentionally dropped into ``None`` — in-worker spans have no
    cross-process recorder to land in; the router (trace owner) records
    attempt spans and emit/migrate events on the fleet clock."""
    from mingpt_distributed_tpu.serving.requests import Request

    kwargs = {f: doc[f] for f in _REQUEST_FIELDS if f in doc}
    kwargs["prompt"] = [int(t) for t in kwargs.get("prompt", ())]
    return Request(**kwargs)


# ---------------------------------------------------------------------
# Size-framed transfer channel
# ---------------------------------------------------------------------

_U64 = struct.Struct(">Q")


def pack_frames(frames: List[Tuple[Dict[str, Any], bytes]]) -> bytes:
    """``[(meta, payload), ...]`` -> one blob. Meta is sorted-key JSON so
    identical migrations serialize byte-identically."""
    out = [FRAME_MAGIC, _U64.pack(len(frames))]
    for meta, payload in frames:
        mb = json.dumps(meta, sort_keys=True).encode()
        out.append(_U64.pack(len(mb)))
        out.append(mb)
        out.append(_U64.pack(len(payload)))
        out.append(payload)
    return b"".join(out)


def unpack_frames(blob: bytes) -> List[Tuple[Dict[str, Any], bytes]]:
    """Inverse of :func:`pack_frames`; raises ``EnvelopeError`` on magic
    drift, truncation, or trailing garbage."""
    if not blob.startswith(FRAME_MAGIC):
        raise EnvelopeError("transfer channel: bad magic")
    pos = len(FRAME_MAGIC)

    def take(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(blob):
            raise EnvelopeError("transfer channel: truncated blob")
        piece = blob[pos:pos + n]
        pos += n
        return piece

    (count,) = _U64.unpack(take(8))
    frames: List[Tuple[Dict[str, Any], bytes]] = []
    for _ in range(count):
        (mlen,) = _U64.unpack(take(8))
        try:
            meta = json.loads(take(mlen).decode())
        except ValueError as e:
            raise EnvelopeError(f"transfer channel: bad meta JSON: {e}")
        if not isinstance(meta, dict):
            raise EnvelopeError("transfer channel: meta must be an object")
        (plen,) = _U64.unpack(take(8))
        frames.append((meta, take(plen)))
    if pos != len(blob):
        raise EnvelopeError(
            f"transfer channel: {len(blob) - pos} trailing bytes")
    return frames


# ---------------------------------------------------------------------
# Fleet authentication (ISSUE 19)
# ---------------------------------------------------------------------


def canonical_bytes(doc: Dict[str, Any]) -> bytes:
    """The byte form an envelope is signed over: sorted-key JSON of the
    document WITHOUT its ``auth`` field. Deterministic by construction —
    the same discipline as the transfer-channel frame meta."""
    body = {k: v for k, v in doc.items() if k != "auth"}
    return json.dumps(body, sort_keys=True).encode()


class FleetAuth:
    """HMAC-SHA256 envelope signer/verifier with monotonic per-sender
    nonces — the shared-secret trust boundary of the cross-host mesh.

    ``sign`` stamps ``doc["auth"] = {"sender", "nonce", "mac"}`` where
    the MAC covers ``canonical_bytes(doc) + sender + nonce``; extra
    fields are open in the envelope grammar, so signed and unsigned
    envelopes validate identically and auth-off stays byte-identical.

    ``verify`` raises typed :class:`AuthError` subclasses and bumps
    ``mingpt_fleet_auth_rejects_total{reason}`` when given a registry:
    missing auth → :class:`UnsignedEnvelope`; MAC mismatch →
    :class:`BadSignature`; a nonce at-or-below the last one seen from
    that sender → :class:`ReplayedNonce`. Nonces are per-sender counters
    (monotonic, not random), so replay detection needs no clock and two
    identical runs verify identically."""

    def __init__(self, secret: str, sender: str, registry=None):
        if not secret:
            raise ValueError("fleet secret must be non-empty")
        self._key = secret.encode()
        self.sender = sender
        self._next_nonce = 0
        self._last_seen: Dict[str, int] = {}
        self._rejects = None
        if registry is not None:
            self._rejects = registry.counter(
                "mingpt_fleet_auth_rejects_total",
                help="envelopes/frames rejected by fleet auth, by reason",
                labels=("reason",))

    def _mac(self, payload: bytes, sender: str, nonce: int) -> str:
        msg = payload + b"|" + sender.encode() + b"|" + str(nonce).encode()
        return hmac.new(self._key, msg, hashlib.sha256).hexdigest()

    def _reject(self, exc_cls, msg: str):
        if self._rejects is not None:
            self._rejects.labels(reason=exc_cls.reason).inc()
        raise exc_cls(msg)

    def sign(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Return ``doc`` with a fresh ``auth`` stamp (mutates in
        place; signing is the last step before serialization)."""
        nonce = self._next_nonce
        self._next_nonce += 1
        doc["auth"] = {"sender": self.sender, "nonce": nonce,
                       "mac": self._mac(canonical_bytes(doc),
                                        self.sender, nonce)}
        return doc

    def verify(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Verify and return ``doc``; typed raise + counter on reject."""
        auth = doc.get("auth")
        if auth is None:
            self._reject(UnsignedEnvelope,
                         f"unsigned envelope kind={doc.get('kind')!r}")
        if (not isinstance(auth, dict)
                or not isinstance(auth.get("sender"), str)
                or not isinstance(auth.get("nonce"), int)
                or isinstance(auth.get("nonce"), bool)
                or not isinstance(auth.get("mac"), str)):
            self._reject(BadSignature, "malformed auth stamp")
        sender, nonce = auth["sender"], auth["nonce"]
        want = self._mac(canonical_bytes(doc), sender, nonce)
        if not hmac.compare_digest(want, auth["mac"]):
            self._reject(BadSignature,
                         f"bad MAC on {doc.get('kind')!r} from {sender}")
        last = self._last_seen.get(sender)
        if last is not None and nonce <= last:
            self._reject(ReplayedNonce,
                         f"replayed nonce {nonce} (last {last}) from "
                         f"{sender}")
        self._last_seen[sender] = nonce
        return doc

    def reject_frame_digest(self, msg: str) -> None:
        """Count + raise a transfer-chunk digest mismatch under the same
        rejects family (reason ``frame_digest``)."""
        if self._rejects is not None:
            self._rejects.labels(reason="frame_digest").inc()
        raise BadSignature(msg)
