"""The procfleet Transport seam (ISSUE 16).

Two implementations of one small surface — JSON envelopes in, JSON
envelopes out, plus raw text/bytes for the metrics page and the
size-framed migration channel:

* :class:`SocketTransport` — real HTTP over a real 127.0.0.1 socket to a
  spawned replica subprocess. Socket timeouts bound every call (the
  allowlisted form of wall-clock coupling in this package: a timeout is
  an OS-level I/O deadline, not a ``time.*`` read); connection failures
  surface as :class:`~.rpc.TransportError` and timeouts as
  :class:`~.rpc.TransportTimeout`, which the supervisor translates into
  crash vs lost-round verdicts. ``stream()`` consumes the worker's
  chunked token stream line by line.

* :class:`LoopbackTransport` — the deterministic in-process twin: the
  same byte-level request/response path (envelopes are serialized to
  JSON bytes and re-parsed, so loopback exercises the exact wire
  encoding) against a :class:`~.worker.ReplicaWorker` held in-process.
  No sockets, no threads, no wall clock — the chaos suite runs on
  :class:`~.fleet.VirtualClock` and two identical runs produce
  byte-identical reports.

Both directions validate every envelope: a malformed document raises
:class:`~.rpc.EnvelopeError` at the boundary it crossed.
"""

from __future__ import annotations

import http.client
import json
import socket
import time
from typing import Any, Callable, Dict, Iterator, Optional

from mingpt_distributed_tpu.serving.procfleet.rpc import (
    EnvelopeError,
    TransportError,
    TransportTimeout,
    TransportUnavailable,
    validate_envelope,
)

__all__ = ["LoopbackTransport", "SocketTransport", "LoopbackHostLink"]


class LoopbackTransport:
    """In-process transport over a :class:`ReplicaWorker` — the
    deterministic half of the seam. Envelopes round-trip through JSON
    bytes so the loopback path is byte-faithful to the socket path."""

    def __init__(self, worker):
        self.worker = worker

    def _dispatch(self, method: str, path: str, body: bytes):
        if self.worker is None:
            raise TransportError("loopback worker is gone (killed)")
        return self.worker.handle(method, path, body)

    def call(self, path: str, doc: Optional[Dict[str, Any]] = None,
             ) -> Dict[str, Any]:
        """POST an envelope (or GET when ``doc`` is None); returns the
        validated response envelope — including ``error`` envelopes,
        which the caller maps to typed exceptions."""
        if doc is None:
            method, body = "GET", b""
        else:
            method = "POST"
            body = json.dumps(validate_envelope(doc), sort_keys=True).encode()
        _status, _ctype, payload = self._dispatch(method, path, body)
        try:
            parsed = json.loads(payload.decode())
        except ValueError as e:
            raise EnvelopeError(f"loopback {path}: non-JSON response: {e}")
        return validate_envelope(parsed)

    def fetch_text(self, path: str) -> str:
        status, _ctype, payload = self._dispatch("GET", path, b"")
        if status != 200:
            raise TransportError(f"loopback GET {path} -> {status}")
        return payload.decode()

    def fetch_json(self, path: str) -> Dict[str, Any]:
        """Raw JSON (non-envelope) endpoints — /attrib."""
        status, _ctype, payload = self._dispatch("GET", path, b"")
        if status != 200:
            raise TransportError(f"loopback GET {path} -> {status}")
        return json.loads(payload.decode())

    def fetch_bytes(self, path: str) -> bytes:
        status, _ctype, payload = self._dispatch("GET", path, b"")
        if status != 200:
            raise TransportError(f"loopback GET {path} -> {status}")
        return payload

    def post_bytes(self, path: str, blob: bytes) -> Dict[str, Any]:
        _status, _ctype, payload = self._dispatch("POST", path, blob)
        return validate_envelope(json.loads(payload.decode()))

    def close(self) -> None:
        self.worker = None


class SocketTransport:
    """Real-HTTP transport to a replica subprocess. One connection per
    call — simple, and robust to the server dying between rounds (a
    kept-alive connection to a SIGKILLed process fails in stranger
    ways). ``timeout_s`` is a socket timeout on connect AND read.

    Connection refused/reset is retried up to ``connect_retries`` times
    with geometric backoff (``sleep`` is injectable per the
    ``RetryPolicy.sleep`` idiom, so tests count delays instead of
    waiting), then surfaces as a typed
    :class:`~.rpc.TransportUnavailable` — distinct from
    :class:`~.rpc.TransportTimeout` because nothing was in flight: the
    caller may safely re-route instead of charging a lost round."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 connect_retries: int = 2, retry_backoff_s: float = 0.05,
                 sleep: Callable[[float], None] = time.sleep):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.connect_retries = connect_retries
        self.retry_backoff_s = retry_backoff_s
        self.sleep = sleep

    def _roundtrip(self, method: str, path: str, body: bytes,
                   timeout_s: Optional[float] = None):
        for attempt in range(self.connect_retries + 1):
            conn = http.client.HTTPConnection(
                self.host, self.port,
                timeout=self.timeout_s if timeout_s is None else timeout_s)
            try:
                conn.request(method, path, body=body or None,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                return resp.status, resp.read()
            except socket.timeout as e:
                raise TransportTimeout(
                    f"{method} {path} to {self.host}:{self.port} timed "
                    f"out: {e}")
            except (ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError) as e:
                if attempt >= self.connect_retries:
                    raise TransportUnavailable(
                        f"{method} {path} to {self.host}:{self.port} "
                        f"unreachable after {attempt + 1} attempts: {e!r}")
                self.sleep(self.retry_backoff_s * (2 ** attempt))
            except (OSError, http.client.HTTPException) as e:
                raise TransportError(
                    f"{method} {path} to {self.host}:{self.port} failed: "
                    f"{e!r}")
            finally:
                conn.close()

    def call(self, path: str, doc: Optional[Dict[str, Any]] = None,
             ) -> Dict[str, Any]:
        if doc is None:
            method, body = "GET", b""
        else:
            method = "POST"
            body = json.dumps(validate_envelope(doc), sort_keys=True).encode()
        _status, payload = self._roundtrip(method, path, body)
        try:
            parsed = json.loads(payload.decode())
        except ValueError as e:
            raise EnvelopeError(f"{path}: non-JSON response: {e}")
        return validate_envelope(parsed)

    def fetch_text(self, path: str) -> str:
        status, payload = self._roundtrip("GET", path, b"")
        if status != 200:
            raise TransportError(f"GET {path} -> HTTP {status}")
        return payload.decode()

    def fetch_json(self, path: str) -> Dict[str, Any]:
        status, payload = self._roundtrip("GET", path, b"")
        if status != 200:
            raise TransportError(f"GET {path} -> HTTP {status}")
        return json.loads(payload.decode())

    def fetch_bytes(self, path: str) -> bytes:
        status, payload = self._roundtrip("GET", path, b"")
        if status != 200:
            raise TransportError(f"GET {path} -> HTTP {status}")
        return payload

    def post_bytes(self, path: str, blob: bytes) -> Dict[str, Any]:
        _status, payload = self._roundtrip(
            "POST", path, blob,
            # migration blobs can be big; give the copy more room than a
            # one-envelope RPC
            timeout_s=self.timeout_s * 4)
        return validate_envelope(json.loads(payload.decode()))

    def stream(self, path: str,
               timeout_s: Optional[float] = None) -> Iterator[Dict[str, Any]]:
        """Consume a chunked token stream: yields validated
        ``stream_token`` envelopes, ends after ``stream_end`` (or an
        ``error`` envelope, which is yielded last)."""
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout_s is None else timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            for raw in resp:
                line = raw.strip()
                if not line:
                    continue
                doc = validate_envelope(json.loads(line.decode()))
                yield doc
                if doc["kind"] in ("stream_end", "error"):
                    return
        except socket.timeout as e:
            raise TransportTimeout(f"stream {path} timed out: {e}")
        except (OSError, http.client.HTTPException) as e:
            raise TransportError(f"stream {path} failed: {e!r}")
        finally:
            conn.close()

    def close(self) -> None:
        pass


class LoopbackHostLink:
    """The multi-host twin of :class:`LoopbackTransport` (ISSUE 19): a
    deterministic in-process link from one :class:`~.hostplane.HostAgent`
    to another. Control-plane envelopes round-trip through JSON bytes
    (byte-faithful to the socket path) and every crossing consults the
    shared :class:`~mingpt_distributed_tpu.training.faults.NetworkFaultInjector`
    first — a partitioned link raises :class:`~.rpc.TransportUnavailable`
    exactly like a refused socket, so the heartbeat ladder can't tell a
    drill from a cable pull.

    Data-plane chunks (:meth:`post_bytes`) are a dumb pipe on purpose:
    the :class:`~.hostplane.PacedChannel` applies link/frame verdicts
    itself *before* handing bytes over, so fault counters advance
    exactly once per chunk."""

    def __init__(self, src: str, dst: str, dst_agent, net=None):
        self.src = src
        self.dst = dst
        self.dst_agent = dst_agent
        self.net = net

    def _require_up(self) -> None:
        if self.dst_agent is None or not getattr(self.dst_agent, "alive",
                                                 True):
            raise TransportUnavailable(
                f"host link {self.src}->{self.dst}: peer host is down")

    def call(self, path: str, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Send one signed control envelope; returns the validated
        response envelope. Partition -> TransportUnavailable."""
        if self.net is not None:
            from mingpt_distributed_tpu.training.faults import \
                LinkPartitioned
            try:
                self.net.link_verdict(self.src, self.dst)
            except LinkPartitioned as e:
                raise TransportUnavailable(str(e))
        self._require_up()
        wire = json.dumps(validate_envelope(doc), sort_keys=True).encode()
        resp = self.dst_agent.handle_host(path, wire)
        return validate_envelope(json.loads(resp.decode()))

    def post_bytes(self, path: str, blob: bytes) -> Dict[str, Any]:
        """Deliver one raw transfer-channel chunk (verdicts already
        applied by the caller); returns the validated ack envelope."""
        self._require_up()
        resp = self.dst_agent.handle_host(path, blob)
        return validate_envelope(json.loads(resp.decode()))

    def close(self) -> None:
        self.dst_agent = None
