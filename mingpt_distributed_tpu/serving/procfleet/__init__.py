"""procfleet — process-isolated replicas over a real socket boundary
with live KV/prefix migration (ISSUE 16).

Layering (each module imports only downward):

* :mod:`.rpc` — the ``mingpt-rpc/1`` envelope grammar + strict
  validator, the request wire form, and the size-framed transfer
  channel for migrated KV/prefix state.
* :mod:`.transport` — the Transport seam: :class:`SocketTransport`
  (real HTTP to a subprocess) and :class:`LoopbackTransport` (the
  byte-faithful deterministic in-process twin).
* :mod:`.worker` — one ``InferenceServer`` behind the RPC surface:
  the step-driven endpoint table, chunked token streaming, migration
  export/import, and the subprocess entry point (hello handshake,
  SIGTERM → exit 75).
* :mod:`.supervisor` — :class:`ProcReplica` / :class:`ProcessSupervisor`
  / :class:`ProcRouter`: the in-process fleet machinery re-based onto
  the boundary, plus ``migrate_and_drain`` live migration.
"""

from mingpt_distributed_tpu.serving.procfleet.rpc import (
    EnvelopeError,
    FRAME_MAGIC,
    RPC_SCHEMA,
    TransportError,
    TransportTimeout,
    envelope,
    pack_frames,
    request_from_wire,
    request_to_wire,
    unpack_frames,
    validate_envelope,
)
from mingpt_distributed_tpu.serving.procfleet.supervisor import (
    LoopbackBackend,
    ProcReplica,
    ProcRouter,
    ProcessBackend,
    ProcessSupervisor,
    ReplicaUnreachable,
    ServerProxy,
    loopback_backend_factory,
    process_backend_factory,
)
from mingpt_distributed_tpu.serving.procfleet.transport import (
    LoopbackTransport,
    SocketTransport,
)
from mingpt_distributed_tpu.serving.procfleet.worker import (
    ReplicaWorker,
    RpcHttpServer,
)

__all__ = [
    "EnvelopeError",
    "FRAME_MAGIC",
    "LoopbackBackend",
    "LoopbackTransport",
    "ProcReplica",
    "ProcRouter",
    "ProcessBackend",
    "ProcessSupervisor",
    "RPC_SCHEMA",
    "ReplicaUnreachable",
    "ReplicaWorker",
    "RpcHttpServer",
    "ServerProxy",
    "SocketTransport",
    "TransportError",
    "TransportTimeout",
    "envelope",
    "loopback_backend_factory",
    "pack_frames",
    "process_backend_factory",
    "request_from_wire",
    "request_to_wire",
    "unpack_frames",
    "validate_envelope",
]
