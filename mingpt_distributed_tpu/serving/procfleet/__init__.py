"""procfleet — process-isolated replicas over a real socket boundary
with live KV/prefix migration (ISSUE 16).

Layering (each module imports only downward):

* :mod:`.rpc` — the ``mingpt-rpc/1`` envelope grammar + strict
  validator, the request wire form, and the size-framed transfer
  channel for migrated KV/prefix state.
* :mod:`.transport` — the Transport seam: :class:`SocketTransport`
  (real HTTP to a subprocess) and :class:`LoopbackTransport` (the
  byte-faithful deterministic in-process twin).
* :mod:`.worker` — one ``InferenceServer`` behind the RPC surface:
  the step-driven endpoint table, chunked token streaming, migration
  export/import, and the subprocess entry point (hello handshake,
  SIGTERM → exit 75).
* :mod:`.supervisor` — :class:`ProcReplica` / :class:`ProcessSupervisor`
  / :class:`ProcRouter`: the in-process fleet machinery re-based onto
  the boundary, plus ``migrate_and_drain`` live migration.
* :mod:`.hostplane` — the cross-host control plane (ISSUE 19):
  :class:`HostAgent` membership/auth/quorum per host,
  :class:`CrossHostRouter` epoch-fenced failover and paced cross-host
  migration, :class:`PacedChannel` bandwidth budgeting, and the
  loopback multi-host mesh builder for deterministic partition drills.
"""

from mingpt_distributed_tpu.serving.procfleet.hostplane import (
    CrossHandle,
    CrossHostRouter,
    HostAgent,
    PacedChannel,
    PacedTransferError,
    build_loopback_fleet,
)
from mingpt_distributed_tpu.serving.procfleet.rpc import (
    AuthError,
    BadSignature,
    EnvelopeError,
    FRAME_MAGIC,
    FleetAuth,
    RPC_SCHEMA,
    ReplayedNonce,
    TransportError,
    TransportTimeout,
    TransportUnavailable,
    UnsignedEnvelope,
    canonical_bytes,
    envelope,
    pack_frames,
    request_from_wire,
    request_to_wire,
    unpack_frames,
    validate_envelope,
)
from mingpt_distributed_tpu.serving.procfleet.supervisor import (
    LoopbackBackend,
    ProcReplica,
    ProcRouter,
    ProcessBackend,
    ProcessSupervisor,
    ReplicaUnreachable,
    ServerProxy,
    loopback_backend_factory,
    process_backend_factory,
)
from mingpt_distributed_tpu.serving.procfleet.transport import (
    LoopbackHostLink,
    LoopbackTransport,
    SocketTransport,
)
from mingpt_distributed_tpu.serving.procfleet.worker import (
    ReplicaWorker,
    RpcHttpServer,
)

__all__ = [
    "AuthError",
    "BadSignature",
    "CrossHandle",
    "CrossHostRouter",
    "EnvelopeError",
    "FRAME_MAGIC",
    "FleetAuth",
    "HostAgent",
    "LoopbackBackend",
    "LoopbackHostLink",
    "LoopbackTransport",
    "PacedChannel",
    "PacedTransferError",
    "ProcReplica",
    "ProcRouter",
    "ProcessBackend",
    "ProcessSupervisor",
    "RPC_SCHEMA",
    "ReplayedNonce",
    "ReplicaUnreachable",
    "ReplicaWorker",
    "RpcHttpServer",
    "ServerProxy",
    "SocketTransport",
    "TransportError",
    "TransportTimeout",
    "TransportUnavailable",
    "UnsignedEnvelope",
    "build_loopback_fleet",
    "canonical_bytes",
    "envelope",
    "loopback_backend_factory",
    "pack_frames",
    "process_backend_factory",
    "request_from_wire",
    "request_to_wire",
    "unpack_frames",
    "validate_envelope",
]
