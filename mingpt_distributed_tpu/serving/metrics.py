"""Serving observability, in the style of training/metrics.py.

Counters (requests submitted/completed, prefills, tokens generated),
per-step gauges (queue depth, slot utilization), and per-request latency
(time-to-first-token, mean inter-token latency). Tokens/sec is computed
over log windows with the same ``RateWindow`` the training MetricsLogger
uses, so the two subsystems report rates with identical semantics.

ISSUE 5: every number here is now a typed instrument registered in a
:class:`~..telemetry.MetricsRegistry` under ``mingpt_serve_*`` — no
private accumulator dicts. TTFT / ITL / admission-stall / prefill-chunk
latencies are fixed-ladder histograms (``LATENCY_BUCKETS_S``), request
outcomes are one labeled counter family, and the padded-bucket fit is a
``bucket``-labeled counter. The pre-existing attribute surface
(``metrics.requests_completed``, ``metrics.bucket_histogram``, ...) is
preserved as read-only views over the instruments, and ``summary()`` /
``log_line()`` emit the same shapes as before.

Output surfaces: a periodic one-line log (``log_every`` scheduler steps,
process-stdout, same pipe-separated shape as the trainer's step line),
an on-demand JSON summary (``summary()`` / ``write_json()``) for offline
batch runs and the serve.py ``--selftest`` gate, and — when the process
registry is injected — the shared Prometheus ``/metrics`` page.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from mingpt_distributed_tpu.telemetry import (
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    RateWindow,
    log_event,
)


class ServingMetrics:
    def __init__(
        self,
        n_slots: int,
        log_every: int = 0,
        enabled: bool = True,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.n_slots = max(n_slots, 1)
        self.log_every = log_every
        self.enabled = enabled
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        # counters
        self._requests = r.counter(
            "mingpt_serve_requests_total",
            help="requests by outcome (submitted counts admissions to the "
                 "queue; rejected = bounded-queue refusals; expired = "
                 "deadline hits; failed = on_token callback raised)",
            labels=("outcome",),
        )
        self._prefills = r.counter(
            "mingpt_serve_prefills_total", help="admissions fully prefilled")
        self._tokens = r.counter(
            "mingpt_serve_tokens_generated_total",
            help="decode tokens emitted")
        # fleet-facing rejection family (ISSUE 6): every refused admission
        # lands here with WHY it was refused — queue_full (bounded queue),
        # shed (global depth watermark), breaker_open (no replica's
        # circuit breaker admits traffic), deadline (cannot be met),
        # draining (graceful shutdown). The legacy outcome="rejected"
        # counter keeps aggregating them all.
        self._rejected = r.counter(
            "mingpt_serving_rejected_total",
            help="refused admissions by reason (queue_full | shed | "
                 "breaker_open | deadline | draining)",
            labels=("reason",),
        )
        for _reason in ("queue_full", "shed", "breaker_open",
                        "deadline", "draining"):
            # pre-touch so every reason is scrape-visible at zero
            self._rejected.labels(reason=_reason).inc(0)
        self._steps = r.counter(
            "mingpt_serve_steps_total", help="scheduler rounds executed")
        # prefill accounting (ISSUE 3): real prompt tokens forwarded, the
        # padded bucket fit (how well the ladder matches the traffic), and
        # wall time inside prefill calls — the decode-stall budget
        # admissions consume
        self._prefill_chunks = r.counter(
            "mingpt_serve_prefill_chunks_total",
            help="padded prefill calls issued")
        self._prefill_tokens = r.counter(
            "mingpt_serve_prefill_tokens_total",
            help="real (unpadded) prompt tokens prefilled")
        self._prefill_padded = r.counter(
            "mingpt_serve_prefill_padded_tokens_total",
            help="bucket lengths actually forwarded (incl. padding and "
                 "shifted-final-chunk overlap)")
        self._prefill_seconds = r.counter(
            "mingpt_serve_prefill_seconds_total",
            help="wall seconds spent inside prefill calls")
        self._bucket_counter = r.counter(
            "mingpt_serve_prefill_bucket_total",
            help="prefill chunks by padded bucket length",
            labels=("bucket",),
        )
        # shared-prefix store
        self._prefix_lookups = r.counter(
            "mingpt_serve_prefix_lookups_total",
            help="prefix-cache lookups at admission")
        self._prefix_hits = r.counter(
            "mingpt_serve_prefix_hits_total", help="prefix-cache hits")
        self._prefix_rows = r.counter(
            "mingpt_serve_prefix_rows_reused_total",
            help="KV rows restored from the prefix cache instead of "
                 "recomputed")
        # latency histograms (fixed ladder — comparable across scrapes)
        self._ttft = r.histogram(
            "mingpt_serve_ttft_seconds",
            help="time to first token per admission",
            buckets=LATENCY_BUCKETS_S,
        )
        self._itl = r.histogram(
            "mingpt_serve_itl_seconds",
            help="mean inter-token latency per completed request",
            buckets=LATENCY_BUCKETS_S,
        )
        self._stall = r.histogram(
            "mingpt_serve_admission_stall_seconds",
            help="slot claim to first token — decode stall an admission "
                 "costs its co-tenants",
            buckets=LATENCY_BUCKETS_S,
        )
        self._chunk_hist = r.histogram(
            "mingpt_serve_prefill_chunk_seconds",
            help="wall time of one padded prefill call",
            buckets=LATENCY_BUCKETS_S,
        )
        # speculative decoding (serving/speculative.py): proposal volume,
        # acceptance, and the emitted-tokens-per-verify distribution — the
        # number that says what speculation actually bought per compiled
        # target forward (1 = draft useless, k+1 = full acceptance)
        self._spec_rounds = r.counter(
            "mingpt_serve_spec_rounds_total",
            help="verify rounds executed (one batched target forward each)")
        self._spec_proposed = r.counter(
            "mingpt_serve_spec_proposed_total",
            help="draft tokens proposed across verify rounds")
        self._spec_accepted = r.counter(
            "mingpt_serve_spec_accepted_total",
            help="draft tokens accepted (matched the target's greedy "
                 "choice)")
        self._spec_tokens_per_verify = r.histogram(
            "mingpt_serve_spec_tokens_per_verify",
            help="tokens emitted per verify round (accepted prefix + the "
                 "bonus token)",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16),
        )
        self._spec_accept_rate = r.gauge(
            "mingpt_serve_spec_accept_rate",
            help="cumulative accepted/proposed draft tokens")
        self._spec_prime = r.counter(
            "mingpt_serve_spec_prime_total",
            help="draft primes by path: full = paid a draft prefill, "
                 "adopted = resumed from migrated draft rows (ISSUE 17)",
            labels=("mode",))
        for mode in ("full", "adopted"):
            self._spec_prime.labels(mode=mode).inc(0)
        # gauges sampled at step boundaries
        self._queue_depth = r.gauge(
            "mingpt_serve_queue_depth", help="queued requests after the "
            "last scheduler round")
        self._slots_active = r.gauge(
            "mingpt_serve_slots_active", help="occupied slots after the "
            "last scheduler round")
        self._util = r.gauge(
            "mingpt_serve_slot_utilization",
            help="mean fraction of decode lanes doing useful work")
        self._tps = r.gauge(
            "mingpt_serve_tokens_per_sec",
            help="decode tokens/sec over the last log window")
        self._prefill_tps = r.gauge(
            "mingpt_serve_prefill_tokens_per_sec",
            help="real prompt tokens/sec over the last prefill window")
        self._hit_rate = r.gauge(
            "mingpt_serve_prefix_hit_rate",
            help="prefix-cache hits / lookups so far")
        self._util_sum = 0.0
        self._prefill_rate = RateWindow()
        self._prefill_tokens_per_sec: Optional[float] = None
        self._rate = RateWindow()
        self._tokens_per_sec: Optional[float] = None

    # -- back-compat attribute views over the instruments ---------------
    @property
    def requests_submitted(self) -> int:
        return int(self._requests.labels(outcome="submitted").value)

    @property
    def requests_completed(self) -> int:
        return int(self._requests.labels(outcome="completed").value)

    @property
    def requests_rejected(self) -> int:
        return int(self._requests.labels(outcome="rejected").value)

    @property
    def requests_expired(self) -> int:
        return int(self._requests.labels(outcome="expired").value)

    @property
    def requests_failed(self) -> int:
        return int(self._requests.labels(outcome="failed").value)

    @property
    def prefills(self) -> int:
        return int(self._prefills.value)

    @property
    def tokens_generated(self) -> int:
        return int(self._tokens.value)

    @property
    def steps(self) -> int:
        return int(self._steps.value)

    @property
    def prefill_chunks(self) -> int:
        return int(self._prefill_chunks.value)

    @property
    def prefill_tokens(self) -> int:
        return int(self._prefill_tokens.value)

    @property
    def prefill_padded_tokens(self) -> int:
        return int(self._prefill_padded.value)

    @property
    def bucket_histogram(self) -> Dict[int, int]:
        return {
            int(labels["bucket"]): int(child.value)
            for labels, child in self._bucket_counter.children()
        }

    @property
    def prefix_lookups(self) -> int:
        return int(self._prefix_lookups.value)

    @property
    def prefix_hits(self) -> int:
        return int(self._prefix_hits.value)

    @property
    def prefix_rows_reused(self) -> int:
        return int(self._prefix_rows.value)

    @property
    def queue_depth(self) -> int:
        return int(self._queue_depth.value)

    @property
    def slots_active(self) -> int:
        return int(self._slots_active.value)

    # -- event hooks (called by the scheduler) -------------------------
    def on_submit(self) -> None:
        self._requests.labels(outcome="submitted").inc()

    def on_reject(self, reason: str = "queue_full") -> None:
        self._requests.labels(outcome="rejected").inc()
        self._rejected.labels(reason=reason).inc()

    def on_expire(self) -> None:
        self._requests.labels(outcome="expired").inc()

    def on_error(self) -> None:
        self._requests.labels(outcome="failed").inc()

    def on_prefill(self, ttft_s: float, stall_s: float = 0.0) -> None:
        """One admission finished prefilling. ``stall_s`` is the wall time
        from slot claim to first token — what this admission cost its
        co-tenants in decode stall."""
        self._prefills.inc()
        self._ttft.observe(ttft_s)
        self._stall.observe(stall_s)

    def on_prefill_chunk(self, n_tokens: int, bucket: int, seconds: float) -> None:
        """One prefill call: ``n_tokens`` real prompt tokens forwarded as
        a ``bucket``-length padded chunk."""
        self._prefill_chunks.inc()
        self._prefill_tokens.inc(n_tokens)
        self._prefill_padded.inc(bucket)
        self._bucket_counter.labels(bucket=bucket).inc()
        self._prefill_seconds.inc(seconds)
        self._chunk_hist.observe(seconds)
        rate = self._prefill_rate.observe(self.prefill_tokens)
        if rate is not None:
            self._prefill_tokens_per_sec = rate
            self._prefill_tps.set(rate)

    def on_prefix_lookup(self, hit: bool, rows: int, enabled: bool = True) -> None:
        if not enabled:
            return
        self._prefix_lookups.inc()
        if hit:
            self._prefix_hits.inc()
            self._prefix_rows.inc(rows)
        self._hit_rate.set(self.prefix_hits / self.prefix_lookups)

    def on_tokens(self, n: int) -> None:
        self._tokens.inc(n)

    def on_spec_prime(self, mode: str) -> None:
        """One draft prime: ``mode`` is ``"full"`` (paid a prefill) or
        ``"adopted"`` (resumed from migrated draft rows)."""
        self._spec_prime.labels(mode=mode).inc()

    def on_spec_round(self, proposed: int, emitted: int) -> None:
        """One verify round on one slot: ``proposed`` = k draft tokens
        offered, ``emitted`` = accepted prefix + bonus token (>= 1), so
        accepted draft tokens = emitted - 1."""
        self._spec_rounds.inc()
        self._spec_proposed.inc(proposed)
        self._spec_accepted.inc(emitted - 1)
        self._spec_tokens_per_verify.observe(emitted)
        if self.spec_proposed:
            self._spec_accept_rate.set(
                self.spec_accepted / self.spec_proposed)

    def on_complete(self, n_generated: int, gen_span_s: float) -> None:
        """gen_span_s: first-token to last-token wall time."""
        self._requests.labels(outcome="completed").inc()
        if n_generated > 1:
            self._itl.observe(gen_span_s / (n_generated - 1))

    def on_step(
        self, queue_depth: int, slots_active: int, lanes_used: Optional[int] = None
    ) -> None:
        """queue_depth/slots_active: end-of-round gauges (occupancy after
        retirement). lanes_used: slots that actually decoded this step —
        what utilization of the shared decode batch means."""
        self._steps.inc()
        self._queue_depth.set(queue_depth)
        self._slots_active.set(slots_active)
        used = slots_active if lanes_used is None else lanes_used
        self._util_sum += used / self.n_slots
        self._util.set(self._util_sum / self.steps)
        rate = self._rate.observe(self.tokens_generated)
        if rate is not None:
            self._tokens_per_sec = rate
            self._tps.set(rate)
        if self.enabled and self.log_every and self.steps % self.log_every == 0:
            log_event(self.log_line())

    # -- read-out ------------------------------------------------------
    @property
    def ttft_mean_s(self) -> Optional[float]:
        return self._ttft.sum / self._ttft.count if self._ttft.count else None

    @property
    def itl_mean_s(self) -> Optional[float]:
        return self._itl.sum / self._itl.count if self._itl.count else None

    @property
    def ttft_p99_s(self) -> Optional[float]:
        """Ladder-resolution p99 (upper bound) — the health-gate signal."""
        return self._ttft.quantile(0.99)

    @property
    def itl_p99_s(self) -> Optional[float]:
        """Ladder-resolution p99 (upper bound) — the health-gate signal."""
        return self._itl.quantile(0.99)

    @property
    def rejected_by_reason(self) -> Dict[str, int]:
        return {
            labels["reason"]: int(child.value)
            for labels, child in self._rejected.children()
        }

    @property
    def spec_rounds(self) -> int:
        return int(self._spec_rounds.value)

    @property
    def spec_proposed(self) -> int:
        return int(self._spec_proposed.value)

    @property
    def spec_accepted(self) -> int:
        return int(self._spec_accepted.value)

    @property
    def spec_accept_rate(self) -> Optional[float]:
        if not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    @property
    def spec_tokens_per_verify_mean(self) -> Optional[float]:
        h = self._spec_tokens_per_verify
        return h.sum / h.count if h.count else None

    @property
    def admission_stall_mean_s(self) -> Optional[float]:
        return self._stall.sum / self.prefills if self.prefills else None

    @property
    def prefix_hit_rate(self) -> Optional[float]:
        if not self.prefix_lookups:
            return None
        return self.prefix_hits / self.prefix_lookups

    @property
    def prefill_pad_overhead(self) -> Optional[float]:
        """Padded-to-real token ratio — 1.0 means the ladder fits the
        traffic perfectly; the redundant-overlap rows of shifted final
        chunks count as padding here too."""
        if not self.prefill_tokens:
            return None
        return self.prefill_padded_tokens / self.prefill_tokens

    @property
    def slot_utilization(self) -> Optional[float]:
        return self._util_sum / self.steps if self.steps else None

    def log_line(self) -> str:
        parts = [
            f"serve step {self.steps}",
            f"active {self.slots_active}/{self.n_slots}",
            f"queued {self.queue_depth}",
            f"done {self.requests_completed}/{self.requests_submitted}",
            f"tokens {self.tokens_generated}",
        ]
        dropped = (self.requests_rejected + self.requests_expired
                   + self.requests_failed)
        if dropped:
            parts.append(
                f"dropped {dropped} (rej {self.requests_rejected} / exp "
                f"{self.requests_expired} / err {self.requests_failed})"
            )
        if self._tokens_per_sec is not None:
            parts.append(f"tokens/sec {self._tokens_per_sec:.4g}")
        if self._prefill_tokens_per_sec is not None:
            parts.append(f"prefill_tok/s {self._prefill_tokens_per_sec:.4g}")
        if self.ttft_mean_s is not None:
            parts.append(f"ttft_ms {self.ttft_mean_s * 1e3:.4g}")
        if self.itl_mean_s is not None:
            parts.append(f"itl_ms {self.itl_mean_s * 1e3:.4g}")
        if self.prefix_lookups:
            parts.append(
                f"prefix_hit {self.prefix_hits}/{self.prefix_lookups}")
        if self.spec_rounds:
            parts.append(
                f"spec_accept {self.spec_accepted}/{self.spec_proposed}")
        return " | ".join(parts)

    def summary(self) -> Dict[str, Any]:
        return {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_rejected": self.requests_rejected,
            "requests_expired": self.requests_expired,
            "requests_failed": self.requests_failed,
            "prefills": self.prefills,
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens": self.prefill_tokens,
            "prefill_padded_tokens": self.prefill_padded_tokens,
            "prefill_pad_overhead": self.prefill_pad_overhead,
            "prefill_time_s": self._prefill_seconds.value,
            "prefill_tokens_per_sec": self._prefill_tokens_per_sec,
            "bucket_histogram": {
                str(k): v for k, v in sorted(self.bucket_histogram.items())
            },
            "admission_stall_mean_s": self.admission_stall_mean_s,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": self.prefix_hit_rate,
            "prefix_rows_reused": self.prefix_rows_reused,
            "tokens_generated": self.tokens_generated,
            "steps": self.steps,
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "slot_utilization": self.slot_utilization,
            "tokens_per_sec": self._tokens_per_sec,
            "ttft_mean_s": self.ttft_mean_s,
            "itl_mean_s": self.itl_mean_s,
            "spec_rounds": self.spec_rounds,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": self.spec_accept_rate,
            "spec_tokens_per_verify_mean": self.spec_tokens_per_verify_mean,
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
            f.write("\n")
