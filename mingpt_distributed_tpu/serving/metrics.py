"""Serving observability, in the style of training/metrics.py.

Counters (requests submitted/completed, prefills, tokens generated),
per-step gauges (queue depth, slot utilization), and per-request latency
(time-to-first-token, mean inter-token latency). Tokens/sec is computed
over log windows with the same ``RateWindow`` the training MetricsLogger
uses, so the two subsystems report rates with identical semantics.

Output surfaces: a periodic one-line log (``log_every`` scheduler steps,
process-stdout, same pipe-separated shape as the trainer's step line) and
an on-demand JSON summary (``summary()`` / ``write_json()``) for offline
batch runs and the serve.py ``--selftest`` gate.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from mingpt_distributed_tpu.training.metrics import RateWindow


class ServingMetrics:
    def __init__(self, n_slots: int, log_every: int = 0, enabled: bool = True):
        self.n_slots = max(n_slots, 1)
        self.log_every = log_every
        self.enabled = enabled
        # counters
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_rejected = 0   # bounded-queue submit refusals
        self.requests_expired = 0    # deadline hits (queued or mid-decode)
        self.requests_failed = 0     # on_token callback raised
        self.prefills = 0
        self.tokens_generated = 0
        self.steps = 0
        # prefill accounting (ISSUE 3): real prompt tokens forwarded, the
        # padded bucket histogram (how well the ladder fits the traffic),
        # and wall time spent inside prefill calls — the decode-stall
        # budget admissions consume
        self.prefill_chunks = 0
        self.prefill_tokens = 0          # real (unpadded) prompt tokens
        self.prefill_padded_tokens = 0   # bucket lengths actually forwarded
        self.bucket_histogram: Dict[int, int] = {}
        self._prefill_time_s = 0.0
        self._prefill_rate = RateWindow()
        self._prefill_tokens_per_sec: Optional[float] = None
        # shared-prefix store
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_rows_reused = 0
        # latency accumulators (seconds)
        self._ttft_sum = 0.0
        self._ttft_count = 0
        self._stall_sum = 0.0            # per-admission slot-claim → first token
        self._itl_sum = 0.0
        self._itl_count = 0
        # gauges sampled at step boundaries
        self.queue_depth = 0
        self.slots_active = 0
        self._util_sum = 0.0
        self._rate = RateWindow()
        self._tokens_per_sec: Optional[float] = None

    # -- event hooks (called by the scheduler) -------------------------
    def on_submit(self) -> None:
        self.requests_submitted += 1

    def on_reject(self) -> None:
        self.requests_rejected += 1

    def on_expire(self) -> None:
        self.requests_expired += 1

    def on_error(self) -> None:
        self.requests_failed += 1

    def on_prefill(self, ttft_s: float, stall_s: float = 0.0) -> None:
        """One admission finished prefilling. ``stall_s`` is the wall time
        from slot claim to first token — what this admission cost its
        co-tenants in decode stall."""
        self.prefills += 1
        self._ttft_sum += ttft_s
        self._ttft_count += 1
        self._stall_sum += stall_s

    def on_prefill_chunk(self, n_tokens: int, bucket: int, seconds: float) -> None:
        """One prefill call: ``n_tokens`` real prompt tokens forwarded as
        a ``bucket``-length padded chunk."""
        self.prefill_chunks += 1
        self.prefill_tokens += n_tokens
        self.prefill_padded_tokens += bucket
        self.bucket_histogram[bucket] = self.bucket_histogram.get(bucket, 0) + 1
        self._prefill_time_s += seconds
        rate = self._prefill_rate.observe(self.prefill_tokens)
        if rate is not None:
            self._prefill_tokens_per_sec = rate

    def on_prefix_lookup(self, hit: bool, rows: int, enabled: bool = True) -> None:
        if not enabled:
            return
        self.prefix_lookups += 1
        if hit:
            self.prefix_hits += 1
            self.prefix_rows_reused += rows

    def on_tokens(self, n: int) -> None:
        self.tokens_generated += n

    def on_complete(self, n_generated: int, gen_span_s: float) -> None:
        """gen_span_s: first-token to last-token wall time."""
        self.requests_completed += 1
        if n_generated > 1:
            self._itl_sum += gen_span_s / (n_generated - 1)
            self._itl_count += 1

    def on_step(
        self, queue_depth: int, slots_active: int, lanes_used: Optional[int] = None
    ) -> None:
        """queue_depth/slots_active: end-of-round gauges (occupancy after
        retirement). lanes_used: slots that actually decoded this step —
        what utilization of the shared decode batch means."""
        self.steps += 1
        self.queue_depth = queue_depth
        self.slots_active = slots_active
        used = slots_active if lanes_used is None else lanes_used
        self._util_sum += used / self.n_slots
        rate = self._rate.observe(self.tokens_generated)
        if rate is not None:
            self._tokens_per_sec = rate
        if self.enabled and self.log_every and self.steps % self.log_every == 0:
            print(self.log_line(), flush=True)

    # -- read-out ------------------------------------------------------
    @property
    def ttft_mean_s(self) -> Optional[float]:
        return self._ttft_sum / self._ttft_count if self._ttft_count else None

    @property
    def itl_mean_s(self) -> Optional[float]:
        return self._itl_sum / self._itl_count if self._itl_count else None

    @property
    def admission_stall_mean_s(self) -> Optional[float]:
        return self._stall_sum / self.prefills if self.prefills else None

    @property
    def prefix_hit_rate(self) -> Optional[float]:
        if not self.prefix_lookups:
            return None
        return self.prefix_hits / self.prefix_lookups

    @property
    def prefill_pad_overhead(self) -> Optional[float]:
        """Padded-to-real token ratio — 1.0 means the ladder fits the
        traffic perfectly; the redundant-overlap rows of shifted final
        chunks count as padding here too."""
        if not self.prefill_tokens:
            return None
        return self.prefill_padded_tokens / self.prefill_tokens

    @property
    def slot_utilization(self) -> Optional[float]:
        return self._util_sum / self.steps if self.steps else None

    def log_line(self) -> str:
        parts = [
            f"serve step {self.steps}",
            f"active {self.slots_active}/{self.n_slots}",
            f"queued {self.queue_depth}",
            f"done {self.requests_completed}/{self.requests_submitted}",
            f"tokens {self.tokens_generated}",
        ]
        dropped = (self.requests_rejected + self.requests_expired
                   + self.requests_failed)
        if dropped:
            parts.append(
                f"dropped {dropped} (rej {self.requests_rejected} / exp "
                f"{self.requests_expired} / err {self.requests_failed})"
            )
        if self._tokens_per_sec is not None:
            parts.append(f"tokens/sec {self._tokens_per_sec:.4g}")
        if self._prefill_tokens_per_sec is not None:
            parts.append(f"prefill_tok/s {self._prefill_tokens_per_sec:.4g}")
        if self.ttft_mean_s is not None:
            parts.append(f"ttft_ms {self.ttft_mean_s * 1e3:.4g}")
        if self.itl_mean_s is not None:
            parts.append(f"itl_ms {self.itl_mean_s * 1e3:.4g}")
        if self.prefix_lookups:
            parts.append(
                f"prefix_hit {self.prefix_hits}/{self.prefix_lookups}")
        return " | ".join(parts)

    def summary(self) -> Dict[str, Any]:
        return {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_rejected": self.requests_rejected,
            "requests_expired": self.requests_expired,
            "requests_failed": self.requests_failed,
            "prefills": self.prefills,
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens": self.prefill_tokens,
            "prefill_padded_tokens": self.prefill_padded_tokens,
            "prefill_pad_overhead": self.prefill_pad_overhead,
            "prefill_time_s": self._prefill_time_s,
            "prefill_tokens_per_sec": self._prefill_tokens_per_sec,
            "bucket_histogram": {
                str(k): v for k, v in sorted(self.bucket_histogram.items())
            },
            "admission_stall_mean_s": self.admission_stall_mean_s,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": self.prefix_hit_rate,
            "prefix_rows_reused": self.prefix_rows_reused,
            "tokens_generated": self.tokens_generated,
            "steps": self.steps,
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "slot_utilization": self.slot_utilization,
            "tokens_per_sec": self._tokens_per_sec,
            "ttft_mean_s": self.ttft_mean_s,
            "itl_mean_s": self.itl_mean_s,
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
            f.write("\n")
