"""Serving observability, in the style of training/metrics.py.

Counters (requests submitted/completed, prefills, tokens generated),
per-step gauges (queue depth, slot utilization), and per-request latency
(time-to-first-token, mean inter-token latency). Tokens/sec is computed
over log windows with the same ``RateWindow`` the training MetricsLogger
uses, so the two subsystems report rates with identical semantics.

Output surfaces: a periodic one-line log (``log_every`` scheduler steps,
process-stdout, same pipe-separated shape as the trainer's step line) and
an on-demand JSON summary (``summary()`` / ``write_json()``) for offline
batch runs and the serve.py ``--selftest`` gate.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from mingpt_distributed_tpu.training.metrics import RateWindow


class ServingMetrics:
    def __init__(self, n_slots: int, log_every: int = 0, enabled: bool = True):
        self.n_slots = max(n_slots, 1)
        self.log_every = log_every
        self.enabled = enabled
        # counters
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_rejected = 0   # bounded-queue submit refusals
        self.requests_expired = 0    # deadline hits (queued or mid-decode)
        self.requests_failed = 0     # on_token callback raised
        self.prefills = 0
        self.tokens_generated = 0
        self.steps = 0
        # latency accumulators (seconds)
        self._ttft_sum = 0.0
        self._ttft_count = 0
        self._itl_sum = 0.0
        self._itl_count = 0
        # gauges sampled at step boundaries
        self.queue_depth = 0
        self.slots_active = 0
        self._util_sum = 0.0
        self._rate = RateWindow()
        self._tokens_per_sec: Optional[float] = None

    # -- event hooks (called by the scheduler) -------------------------
    def on_submit(self) -> None:
        self.requests_submitted += 1

    def on_reject(self) -> None:
        self.requests_rejected += 1

    def on_expire(self) -> None:
        self.requests_expired += 1

    def on_error(self) -> None:
        self.requests_failed += 1

    def on_prefill(self, ttft_s: float) -> None:
        self.prefills += 1
        self._ttft_sum += ttft_s
        self._ttft_count += 1

    def on_tokens(self, n: int) -> None:
        self.tokens_generated += n

    def on_complete(self, n_generated: int, gen_span_s: float) -> None:
        """gen_span_s: first-token to last-token wall time."""
        self.requests_completed += 1
        if n_generated > 1:
            self._itl_sum += gen_span_s / (n_generated - 1)
            self._itl_count += 1

    def on_step(
        self, queue_depth: int, slots_active: int, lanes_used: Optional[int] = None
    ) -> None:
        """queue_depth/slots_active: end-of-round gauges (occupancy after
        retirement). lanes_used: slots that actually decoded this step —
        what utilization of the shared decode batch means."""
        self.steps += 1
        self.queue_depth = queue_depth
        self.slots_active = slots_active
        used = slots_active if lanes_used is None else lanes_used
        self._util_sum += used / self.n_slots
        rate = self._rate.observe(self.tokens_generated)
        if rate is not None:
            self._tokens_per_sec = rate
        if self.enabled and self.log_every and self.steps % self.log_every == 0:
            print(self.log_line(), flush=True)

    # -- read-out ------------------------------------------------------
    @property
    def ttft_mean_s(self) -> Optional[float]:
        return self._ttft_sum / self._ttft_count if self._ttft_count else None

    @property
    def itl_mean_s(self) -> Optional[float]:
        return self._itl_sum / self._itl_count if self._itl_count else None

    @property
    def slot_utilization(self) -> Optional[float]:
        return self._util_sum / self.steps if self.steps else None

    def log_line(self) -> str:
        parts = [
            f"serve step {self.steps}",
            f"active {self.slots_active}/{self.n_slots}",
            f"queued {self.queue_depth}",
            f"done {self.requests_completed}/{self.requests_submitted}",
            f"tokens {self.tokens_generated}",
        ]
        dropped = (self.requests_rejected + self.requests_expired
                   + self.requests_failed)
        if dropped:
            parts.append(
                f"dropped {dropped} (rej {self.requests_rejected} / exp "
                f"{self.requests_expired} / err {self.requests_failed})"
            )
        if self._tokens_per_sec is not None:
            parts.append(f"tokens/sec {self._tokens_per_sec:.4g}")
        if self.ttft_mean_s is not None:
            parts.append(f"ttft_ms {self.ttft_mean_s * 1e3:.4g}")
        if self.itl_mean_s is not None:
            parts.append(f"itl_ms {self.itl_mean_s * 1e3:.4g}")
        return " | ".join(parts)

    def summary(self) -> Dict[str, Any]:
        return {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_rejected": self.requests_rejected,
            "requests_expired": self.requests_expired,
            "requests_failed": self.requests_failed,
            "prefills": self.prefills,
            "tokens_generated": self.tokens_generated,
            "steps": self.steps,
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "slot_utilization": self.slot_utilization,
            "tokens_per_sec": self._tokens_per_sec,
            "ttft_mean_s": self.ttft_mean_s,
            "itl_mean_s": self.itl_mean_s,
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
            f.write("\n")
