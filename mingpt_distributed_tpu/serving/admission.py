"""Pluggable admission ordering (ISSUE 12 policy hook).

The scheduler's admit loop and the fleet router's retry dispatch both
used to hard-code FIFO: ``queue.popleft()`` decided which waiting
request got the next free KV slot. This module extracts that decision
into :class:`AdmissionPolicy` so a policy object — the SAME object —
can drive either a solo ``InferenceServer`` or a ``ReplicaSupervisor``
fleet (pass it to ``InferenceServer(admission_policy=...)`` /
``default_server_factory(..., admission_policy=...)`` and to
``Router(admission_policy=...)``).

Only the *interface* and the behavior-preserving default live here:
:class:`FifoPolicy` selects index 0, which is exactly ``popleft()``,
so a server constructed without a policy is unchanged. The interesting
policies (deadline-aware EDF, fair-share per-tenant) live in
``mingpt_distributed_tpu/trafficlab/policies.py`` with the rest of the
traffic lab.

The contract: ``sort_key(handle, position, now)`` returns a total-order
key over *waiting* handles (smaller = admit sooner). Handles are duck-
typed — both ``RequestHandle`` (scheduler queue) and ``FleetHandle``
(router retry queue) expose ``.deadline`` (absolute clock seconds or
None) and ``.request`` (with ``.tenant``), which is all the shipped
policies read. ``on_admit`` fires when a handle actually claims a KV
slot (the scheduler calls it; the router does NOT, so a fleet-shared
stateful policy counts each admission exactly once).

Determinism: every policy must break ties by queue position
(``sort_key`` includes it), so admission order — and therefore the
whole serving schedule on a virtual clock — is a pure function of the
submitted sequence.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

__all__ = [
    "AdmissionPolicy",
    "FifoPolicy",
    "HealthAwarePolicy",
]


class AdmissionPolicy:
    """Order waiting requests for admission. Subclass and implement
    ``sort_key``; override ``on_admit`` for stateful policies."""

    #: registry/report name (trafficlab reports grade policies by it)
    name = "policy"

    def sort_key(self, handle: Any, position: int,
                 now: float) -> Tuple:  # pragma: no cover - interface
        """Total-order key for one waiting handle (smaller admits
        first). ``position`` is the handle's current queue index — every
        key must include it (last) so equal-priority requests keep FIFO
        order."""
        raise NotImplementedError

    def select(self, queue: Sequence[Any], now: float) -> int:
        """Index of the next handle to admit from ``queue`` (non-empty)."""
        best = 0
        best_key = self.sort_key(queue[0], 0, now)
        for i in range(1, len(queue)):
            key = self.sort_key(queue[i], i, now)
            if key < best_key:
                best, best_key = i, key
        return best

    def order(self, handles: Sequence[Any], now: float) -> List[int]:
        """Indices of ``handles`` in admission order (used by the fleet
        router to drain its retry queue policy-first)."""
        return sorted(range(len(handles)),
                      key=lambda i: self.sort_key(handles[i], i, now))

    def on_admit(self, handle: Any) -> None:
        """A handle claimed a KV slot. Default: stateless no-op."""


class FifoPolicy(AdmissionPolicy):
    """Arrival order — the extracted default. ``select`` always returns
    0, byte-identical to the old ``popleft()`` admission."""

    name = "fifo"

    def sort_key(self, handle: Any, position: int, now: float) -> Tuple:
        return (position,)

    def select(self, queue: Sequence[Any], now: float) -> int:
        return 0


class HealthAwarePolicy(AdmissionPolicy):
    """Admission that consults live fleet health (ISSUE 20): FIFO while
    every routable replica passes its health gates, earliest-deadline-
    first the moment any is degraded (queue over watermark, ITL p99
    over SLO, recompiles — or nothing routable at all).

    The rationale: under healthy capacity, arrival order is the fair
    and cache-friendly order; once the fleet is degraded, head-of-line
    blocking starts costing deadline misses, so ordering flips to
    honour urgency. ``bind`` attaches the signals seam
    (:class:`~mingpt_distributed_tpu.control.signals.FleetSignalsView`
    or anything with ``degraded() -> bool``) after the router exists —
    trafficlab's runner binds it per cell; unbound the policy is plain
    FIFO, so it degrades safely in a solo server.

    The degraded bit is re-read per ``select``/``order`` call, never
    mid-sort: fleet state cannot change inside one ordering pass, so
    every key in a pass comes from the same regime and stays a total
    order."""

    name = "health"

    def __init__(self):
        self._signals = None

    def bind(self, signals) -> None:
        self._signals = signals

    def _degraded(self) -> bool:
        return self._signals is not None and self._signals.degraded()

    def _key(self, handle: Any, position: int, degraded: bool) -> Tuple:
        if not degraded:
            return (0, 0, 0.0, position)
        deadline = getattr(handle, "deadline", None)
        if deadline is None:
            return (1, 1, 0.0, position)
        return (1, 0, float(deadline), position)

    def sort_key(self, handle: Any, position: int, now: float) -> Tuple:
        return self._key(handle, position, self._degraded())

    def select(self, queue: Sequence[Any], now: float) -> int:
        degraded = self._degraded()
        if not degraded:
            return 0
        return min(
            range(len(queue)),
            key=lambda i: self._key(queue[i], i, degraded))

    def order(self, handles: Sequence[Any], now: float) -> List[int]:
        degraded = self._degraded()
        return sorted(
            range(len(handles)),
            key=lambda i: self._key(handles[i], i, degraded))
