"""Compiled prefill / multi-slot decode for the continuous-batching server.

Exactly TWO programs are compiled, once each, for the server's lifetime:

1. **prefill-into-slot** — one forward over a right-padded ``(1,
   prefill_len)`` prompt through ``generate._forward_cached_hidden`` (the
   same unrolled cached-block chain solo ``generate()`` uses), whose
   batch-1 cache is then written whole into the pool at a *traced* slot
   index. Logits are read at the *traced* position ``length - 1`` before
   the LM head, and the first token is sampled on device. Every dynamic
   quantity (slot, prompt length, sampling params, PRNG key) is a traced
   argument, so admitting request #100 reuses request #1's executable.

2. **decode-step** — one token for every slot at once: ``vmap`` over the
   slot axis of the same ``_forward_cached`` the solo scan uses, each lane
   carrying its own absolute position (per-slot ``kv_offset`` and RoPE /
   learned-position index, per-slot one-row cache write — the vmapped
   dynamic_update_slice lowers to a one-row-per-slot scatter, NOT a
   whole-cache rewrite). Per-slot sampling params ride as traced arrays.

Padding correctness: the prompt is right-padded to ``prefill_len``. Causal
masking means real positions never attend a pad position ahead of them,
and a pad position's stale K/V only becomes visible at the decode step
that first *writes* that position with a real token — so garbage is
overwritten before it can ever be attended. Inactive slots keep decoding
masked-out lanes into their own (dead) cache rows; admission prefill
overwrites the slot before reuse.

Sampling parity: the per-slot sampler mirrors ``generate._select_next``
(temperature → top-k → top-p → sample/argmax) with the params as traced
per-slot arrays instead of static python scalars — which is what keeps one
compiled program serving mixed greedy/sampled tenants. For greedy lanes
the filters cannot move the argmax, so a greedy request's tokens match
solo ``generate()`` exactly (tests/test_serving.py asserts token identity).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import generate as gen
from mingpt_distributed_tpu.serving.kv_pool import SlotKVPool


def _select_next_slots(
    logits: jax.Array,      # (S, V) fp32
    keys: jax.Array,        # (S,) typed PRNG keys
    temps: jax.Array,       # (S,) float32
    top_ks: jax.Array,      # (S,) int32, 0 = disabled
    top_ps: jax.Array,      # (S,) float32, >= 1.0 = disabled
    do_sample: jax.Array,   # (S,) bool
) -> jax.Array:
    """generate._select_next with per-slot traced params. Filter order and
    edge semantics (top token always survives top-p; top_k clamped to V)
    match the solo sampler exactly."""
    v = logits.shape[-1]
    logits = logits / jnp.maximum(temps, 1e-8)[:, None]
    # top-k with per-slot k: threshold at the k-th largest value; k=V is a
    # no-op, so "disabled" rides as k_eff = V
    k_eff = jnp.where(top_ks > 0, jnp.minimum(top_ks, v), v)
    desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(desc, (k_eff - 1)[:, None], axis=-1)
    logits = jnp.where(logits < kth, -jnp.inf, logits)
    # nucleus: smallest prefix of the (re-sorted, post-top-k) distribution
    # whose preceding cumulative mass is < top_p; top token unconditional
    desc2 = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc2, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_ps[:, None]
    keep = keep.at[:, 0].set(True)
    kth2 = jnp.min(jnp.where(keep, desc2, jnp.inf), axis=-1, keepdims=True)
    nucleus_on = (top_ps < 1.0)[:, None]
    logits = jnp.where(nucleus_on & (logits < kth2), -jnp.inf, logits)
    sampled = jax.vmap(lambda l, k: jax.random.categorical(k, l))(logits, keys)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(do_sample, sampled, greedy).astype(jnp.int32)


def _prefill_impl(
    params, cache, prompt, length, slot, temp, top_k, top_p, do_sample, key,
    *, cfg: GPTConfig,
):
    """prompt: (prefill_len,) right-padded; length/slot traced scalars.
    Returns (first sampled token (scalar int32), updated pool cache)."""
    scratch = gen.init_cache(cfg, 1, dtype=cache["k"].dtype)
    x, scratch = gen._forward_cached_hidden(params, prompt[None], scratch, 0, cfg)
    h_last = jax.lax.dynamic_slice_in_dim(x, length - 1, 1, axis=1)
    logits = gen._head_logits(params, h_last, cfg)[:, 0]  # (1, V)
    first = _select_next_slots(
        logits, key[None], temp[None], top_k[None], top_p[None],
        do_sample[None],
    )[0]
    # the scratch cache covers the slot's FULL length (zeros past the
    # prompt), so installing it evicts every byte of the previous tenant
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], scratch["k"], (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], scratch["v"], (0, slot, 0, 0, 0)),
    }
    return first, cache


def _decode_impl(
    params, cache, tokens, positions, temps, top_ks, top_ps, do_sample, keys,
    *, cfg: GPTConfig,
):
    """One token for every slot: tokens/positions (S,), sampling arrays
    (S,), keys (S,). Returns (next tokens (S,), updated pool cache)."""
    safe_pos = jnp.clip(positions, 0, cfg.block_size - 1)

    def one_slot(tok, cache_slot, pos):
        # re-grow the batch axis the vmap stripped so the lane is exactly
        # solo generate's (B=1, T=1) decode body
        cache_b = jax.tree.map(lambda a: a[:, None], cache_slot)
        logits, cache_b = gen._forward_cached(
            params, tok[None, None], cache_b, pos, cfg)
        return logits[0], jax.tree.map(lambda a: a[:, 0], cache_b)

    logits, cache = jax.vmap(one_slot, in_axes=(0, 1, 0), out_axes=(0, 1))(
        tokens, cache, safe_pos)
    nxt = _select_next_slots(logits, keys, temps, top_ks, top_ps, do_sample)
    return nxt, cache


class DecodeEngine:
    """Owns the slot pool and the two jitted programs.

    The jit wrappers are per-engine objects so their compile caches count
    only this engine's traces — ``compile_counts()`` is how the tests
    assert the no-recompile-after-warmup guarantee.
    """

    def __init__(
        self,
        params,
        cfg: GPTConfig,
        n_slots: int,
        prefill_len: Optional[int] = None,
        cache_dtype=None,
    ):
        self.cfg = cfg
        self.params = params
        self.prefill_len = int(prefill_len or cfg.block_size)
        if not (1 <= self.prefill_len <= cfg.block_size):
            raise ValueError(
                f"prefill_len {self.prefill_len} outside [1, "
                f"{cfg.block_size}]"
            )
        self.pool = SlotKVPool(cfg, n_slots, cache_dtype)
        self._prefill_jit = jax.jit(
            functools.partial(_prefill_impl, cfg=cfg), donate_argnums=(1,))
        self._decode_jit = jax.jit(
            functools.partial(_decode_impl, cfg=cfg), donate_argnums=(1,))

    @property
    def n_slots(self) -> int:
        return self.pool.n_slots

    def prefill(
        self,
        slot: int,
        prompt_ids: Sequence[int],
        temperature: float,
        top_k: Optional[int],
        top_p: Optional[float],
        do_sample: bool,
        key: jax.Array,
    ) -> int:
        """Prefill ``prompt_ids`` (length <= prefill_len) into ``slot`` and
        return the first sampled/greedy token."""
        n = len(prompt_ids)
        if not (1 <= n <= self.prefill_len):
            raise ValueError(
                f"prompt length {n} outside [1, {self.prefill_len}] "
                "(the scheduler crops before calling)"
            )
        prompt = np.zeros(self.prefill_len, np.int32)
        prompt[:n] = np.asarray(prompt_ids, np.int32)
        first, cache = self._prefill_jit(
            self.params, self.pool.cache, jnp.asarray(prompt),
            np.int32(n), np.int32(slot),
            np.float32(temperature),
            np.int32(0 if top_k is None else top_k),
            np.float32(1.0 if top_p is None else top_p),
            np.bool_(do_sample), key,
        )
        self.pool.cache = cache
        return int(jax.device_get(first))

    def decode_step(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        do_sample: np.ndarray,
        keys: jax.Array,
    ) -> np.ndarray:
        """Advance every slot one token; caller masks inactive lanes."""
        nxt, cache = self._decode_jit(
            self.params, self.pool.cache,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(top_ks, jnp.int32),
            jnp.asarray(top_ps, jnp.float32), jnp.asarray(do_sample),
            keys,
        )
        self.pool.cache = cache
        return np.asarray(jax.device_get(nxt))

    def compile_counts(self) -> Dict[str, int]:
        """Number of distinct traces compiled per program — stays at 1 each
        after warmup no matter how many requests are served."""
        return {
            "prefill": self._prefill_jit._cache_size(),
            "decode": self._decode_jit._cache_size(),
        }
